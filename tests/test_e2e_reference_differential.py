"""End-to-end reference-formula differential (round-2 VERDICT item 7).

Per-kernel oracles cannot catch COMPOSITION errors — a winsorize/subset
ordering slip, a complete-case handling difference, a lag applied at the
wrong layer — because each kernel is verified in isolation. This test
closes that gap: a plain-pandas transcription of the reference's full
``get_factors → winsorize → get_subsets → Table 1 → Table 2`` composition
(``src/calc_Lewellen_2014.py:531-574,44-112,577-868``;
``src/regressions.py:9-130``) runs on the SAME merged monthly panel and
daily data the framework consumes, and the final numerics must agree to
1e-4 (the BASELINE parity bar).

The transcription uses row-wise groupby/rolling pandas semantics — the
reference's computational model — with no imports from the framework's ops
layer. The weekly beta comes from the independent calendar oracle
(``tests/test_beta_calendar_oracle.py``), which shares nothing with the
kernel either.
"""

import math

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.synthetic import SyntheticConfig, generate_synthetic_wrds
from fm_returnprediction_tpu.data.wrds_pull import subset_to_common_stock_and_exchanges
from fm_returnprediction_tpu.models.lewellen import MODELS
from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
from fm_returnprediction_tpu.panel.characteristics import FACTORS_DICT, get_factors
from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
from fm_returnprediction_tpu.panel.transform_compustat import (
    add_report_date,
    calc_book_equity,
    expand_compustat_annual_to_monthly,
    merge_CRSP_and_Compustat,
)
from fm_returnprediction_tpu.panel.transform_crsp import calculate_market_equity
from fm_returnprediction_tpu.reporting.table1 import build_table_1

from test_beta_calendar_oracle import oracle_weekly_betas

ATOL = 1e-4
RTOL = 1e-4


# --------------------------------------------------------------------------
# pandas transcription of the reference composition
# --------------------------------------------------------------------------

def _ref_characteristics(merged: pd.DataFrame) -> pd.DataFrame:
    """The 12 monthly characteristics with the reference's row-shift
    groupby semantics (``src/calc_Lewellen_2014.py:137-341``)."""
    df = merged.sort_values(["permno", "jdate"]).reset_index(drop=True).copy()
    g = df.groupby("permno", sort=False)

    me_lag = g["me"].shift(1)
    be_lag = g["be"].shift(1)
    df["log_size"] = np.log(me_lag)
    df["log_bm"] = np.log(be_lag) - np.log(me_lag)
    df["return_12_2"] = (
        (1.0 + g["retx"].shift(2))
        .groupby(df["permno"], sort=False)
        .rolling(11, min_periods=11)
        .apply(np.prod, raw=True)
        .reset_index(level=0, drop=True)
        - 1.0
    )
    df["accruals_final"] = df["accruals"] - df["depreciation"]
    df["roa"] = df["earnings"] / df["assets"]
    df["log_assets_growth"] = np.log(df["assets"] / g["assets"].shift(12))
    dvc_12 = (
        df.groupby("permno", sort=False)["dvc"]
        .rolling(12, min_periods=1)
        .sum()
        .reset_index(level=0, drop=True)
    )
    df["dy"] = dvc_12 / g["prc"].shift(1)
    lr = np.log1p(df["retx"])
    lr_13 = lr.groupby(df["permno"], sort=False).shift(13)
    df["log_return_13_36"] = (
        lr_13.groupby(df["permno"], sort=False)
        .rolling(24, min_periods=24)
        .sum()
        .reset_index(level=0, drop=True)
    )
    shr_lag = g["shrout"].shift(1)
    df["log_issues_12"] = np.log(shr_lag) - np.log(g["shrout"].shift(12))
    df["log_issues_36"] = np.log(shr_lag) - np.log(g["shrout"].shift(36))
    df["debt_price"] = df["total_debt"] / me_lag
    df["sales_price"] = df["sales"] / me_lag
    return df


def _ref_daily(crsp_d: pd.DataFrame, crsp_index_d: pd.DataFrame) -> pd.DataFrame:
    """Vol-252 (pandas rolling, last obs per month) and the weekly beta
    (independent calendar oracle) as a (permno, jdate) frame."""
    d = crsp_d.sort_values(["permno", "dlycaldt"]).copy()
    vol = (
        d.groupby("permno", sort=False)["retx"]
        .rolling(252, min_periods=100)
        .std()
        .reset_index(level=0, drop=True)
        * math.sqrt(252)
    )
    d = d.assign(_vol=vol, jdate=d["dlycaldt"] + pd.offsets.MonthEnd(0))
    last = d.drop_duplicates(["permno", "jdate"], keep="last")
    vol_frame = last[["permno", "jdate", "_vol"]].rename(columns={"_vol": "rolling_std_252"})

    stock_rows = [
        (int(p), ts.date(), None if pd.isna(r) else float(r))
        for p, ts, r in zip(d["permno"], pd.DatetimeIndex(d["dlycaldt"]), d["retx"])
    ]
    idx = crsp_index_d.drop_duplicates("caldt", keep="last")
    index_rows = {
        ts.date(): (None if pd.isna(v) else float(v))
        for ts, v in zip(pd.DatetimeIndex(idx["caldt"]), idx["vwretx"])
    }
    betas = oracle_weekly_betas(stock_rows, index_rows)
    rows = [
        {"permno": p, "_ym": ym, "beta": (np.nan if b is None else b)}
        for (p, ym), b in betas.items()
    ]
    beta_frame = pd.DataFrame(rows)
    vol_frame = vol_frame.assign(
        _ym=[(ts.year, ts.month) for ts in pd.DatetimeIndex(vol_frame["jdate"])]
    )
    return vol_frame.merge(beta_frame, on=["permno", "_ym"], how="outer")


def _ref_winsorize(df: pd.DataFrame, cols) -> pd.DataFrame:
    """Per-month cross-sectional clip at [1%, 99%], skipping months with
    fewer than 5 valid observations (``src/calc_Lewellen_2014.py:505-529``)."""
    df = df.copy()
    for col in cols:
        def clip_month(s):
            x = s.to_numpy(dtype=float)
            finite = np.isfinite(x)
            if finite.sum() < 5:
                return s
            lo, hi = np.percentile(x[finite], [1.0, 99.0])
            return pd.Series(np.clip(x, lo, hi), index=s.index)

        df[col] = df.groupby("jdate", sort=False)[col].transform(clip_month)
    return df


def _ref_subsets(df: pd.DataFrame):
    """NYSE 20th/50th ME percentile universes (``:44-112``)."""
    nyse = df[df["primaryexch"] == "N"]
    bp = nyse.groupby("jdate")["me"].quantile([0.2, 0.5]).unstack()
    bp = bp.reindex(df["jdate"].unique())
    b20 = df["jdate"].map(bp[0.2])
    b50 = df["jdate"].map(bp[0.5])
    return {
        "All stocks": df,
        "All-but-tiny stocks": df[df["me"] >= b20],
        "Large stocks": df[df["me"] >= b50],
    }


def _ref_table1(subsets, variables_dict):
    """Time-series averages of monthly cross-sectional stats (``:577-670``):
    ±inf as missing, ddof=1 std (months with ≥2 obs), distinct-permno N."""
    out = {}
    for sub_name, sdf in subsets.items():
        for disp, col in variables_dict.items():
            x = sdf[col].replace([np.inf, -np.inf], np.nan)
            by_month = x.groupby(sdf["jdate"])
            means = by_month.mean()
            stds = by_month.std(ddof=1)
            counts = by_month.count()
            avg = means[counts >= 1].mean()
            std = stds[counts >= 2].mean()
            n = sdf.loc[x.notna(), "permno"].nunique()
            out[(sub_name, disp)] = (avg, std, n)
    return out


def _ref_fm(sdf: pd.DataFrame, pred_cols, nw_lags=4, min_months=10):
    """Monthly cross-sectional OLS + FM aggregation
    (``src/regressions.py:9-130``): complete-case dropna, n >= P+1 month
    gate, centered R², NW weight 1 - k/T."""
    cols = ["jdate", "permno", "retx"] + list(pred_cols)
    data = sdf[cols].dropna(subset=["retx"] + list(pred_cols))
    slopes, r2s, ns = {}, [], []
    for month, grp in data.groupby("jdate"):
        n = len(grp)
        if n < len(pred_cols) + 1:
            continue
        y = grp["retx"].to_numpy(dtype=float)
        x = np.column_stack([np.ones(n)] + [grp[c].to_numpy(dtype=float) for c in pred_cols])
        beta, *_ = np.linalg.lstsq(x, y, rcond=None)
        resid = y - x @ beta
        sst = ((y - y.mean()) ** 2).sum()
        r2 = 1.0 - (resid @ resid) / sst if sst > 0 else 0.0
        slopes[month] = beta[1:]
        r2s.append(r2)
        ns.append(n)
    if not slopes:
        return None
    slope_df = pd.DataFrame.from_dict(slopes, orient="index", columns=list(pred_cols)).sort_index()

    coefs, tstats = {}, {}
    for c in pred_cols:
        s = slope_df[c].dropna().to_numpy(dtype=float)
        t = len(s)
        if t < min_months:
            coefs[c], tstats[c] = np.nan, np.nan
            continue
        mu = s.mean()
        u = s - mu
        gamma0 = u @ u
        acc = 0.0
        for k in range(1, nw_lags + 1):
            if k < t:
                acc += max(1.0 - k / t, 0.0) * (u[k:] @ u[:-k])
        # np.sqrt of a negative NW variance (possible under the 1 - k/T
        # weights on short series) is NaN, as in the reference — not a crash
        with np.errstate(invalid="ignore"):
            se = float(np.sqrt((gamma0 + 2.0 * acc) / t**2))
        coefs[c] = mu
        tstats[c] = mu / se if se > 0 else np.nan
    return {
        "coef": coefs,
        "tstat": tstats,
        "mean_r2": float(np.mean(r2s)),
        "mean_n": float(np.mean(ns)),
    }


# --------------------------------------------------------------------------
# the differential
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def universe():
    # 90 firms × 72 months: rich enough that EVERY (model, subset) Table-2
    # cell runs with >= min_months valid months and zero NaN slopes — Model
    # 3's 14 predictors need >= 15 complete-case firms per month inside the
    # Large subset and >= 37 months of history (round-3 verdict item 7; the
    # old 40×60 fixture NaN-skipped the two hardest cells).
    data = generate_synthetic_wrds(SyntheticConfig(n_firms=90, n_months=72))
    crsp_m = subset_to_common_stock_and_exchanges(data["crsp_m"])
    crsp_d = subset_to_common_stock_and_exchanges(data["crsp_d"])
    crsp = calculate_market_equity(crsp_m)
    comp = add_report_date(data["comp"].copy())
    comp = calc_book_equity(comp)
    comp = expand_compustat_annual_to_monthly(comp)
    merged = merge_CRSP_and_Compustat(crsp, comp, data["ccm"])
    if "mthcaldt" not in merged.columns:
        merged["mthcaldt"] = merged["jdate"]
    return merged, crsp_d, data["crsp_index_d"]


@pytest.fixture(scope="module")
def framework_side(universe):
    merged, crsp_d, index_d = universe
    panel, factors_dict = get_factors(merged, crsp_d, index_d, dtype=np.float64)
    masks = compute_subset_masks(panel)
    return panel, factors_dict, masks


@pytest.fixture(scope="module")
def reference_side(universe):
    merged, crsp_d, index_d = universe
    df = _ref_characteristics(merged)
    daily = _ref_daily(crsp_d, index_d)
    df["_ym"] = [(ts.year, ts.month) for ts in pd.DatetimeIndex(df["jdate"])]
    df = df.merge(
        daily[["permno", "_ym", "rolling_std_252"]], on=["permno", "_ym"], how="left"
    ).merge(
        daily[["permno", "_ym", "beta"]].dropna(subset=["beta"]),
        on=["permno", "_ym"], how="left",
    )
    df = _ref_winsorize(df, list(FACTORS_DICT.values()))
    return df


def test_table1_matches_reference_transcription(framework_side, reference_side):
    panel, factors_dict, masks = framework_side
    table = build_table_1(panel, masks, factors_dict)
    want = _ref_table1(_ref_subsets(reference_side), factors_dict)

    checked = 0
    for (sub, disp), (avg, std, n) in want.items():
        got_avg = table.loc[disp, (sub, "Avg")]
        got_std = table.loc[disp, (sub, "Std")]
        got_n = table.loc[disp, (sub, "N")]
        np.testing.assert_allclose(got_avg, avg, rtol=RTOL, atol=ATOL,
                                   err_msg=f"Avg {sub}/{disp}")
        np.testing.assert_allclose(got_std, std, rtol=RTOL, atol=ATOL,
                                   err_msg=f"Std {sub}/{disp}")
        assert int(got_n) == int(n), f"N {sub}/{disp}: {got_n} vs {n}"
        checked += 1
    assert checked == len(factors_dict) * 3


def test_table2_fm_matches_reference_transcription(framework_side, reference_side):
    panel, factors_dict, masks = framework_side
    subsets = _ref_subsets(reference_side)

    y = jnp.asarray(panel.var("retx"))
    checked = 0
    nan_cells = 0
    for model in MODELS:
        pred_cols = [factors_dict[d] for d in model.predictors]
        x = jnp.asarray(panel.select(pred_cols))
        for sub_name, mask in masks.items():
            cs, summary = fama_macbeth(y, x, jnp.asarray(mask))
            want = _ref_fm(subsets[sub_name], pred_cols)
            assert want is not None, (
                f"{model.name}/{sub_name}: no valid months — fixture too "
                "small for a real comparison"
            )
            for i, c in enumerate(pred_cols):
                got = float(np.asarray(summary.coef)[i])
                wc = want["coef"][c]
                if np.isnan(wc):
                    nan_cells += 1
                    assert np.isnan(got), f"{model.name}/{sub_name}/{c}"
                else:
                    np.testing.assert_allclose(
                        got, wc, rtol=RTOL, atol=ATOL,
                        err_msg=f"coef {model.name}/{sub_name}/{c}",
                    )
                    np.testing.assert_allclose(
                        float(np.asarray(summary.tstat)[i]), want["tstat"][c],
                        rtol=1e-3, atol=1e-3,
                        err_msg=f"tstat {model.name}/{sub_name}/{c}",
                    )
            np.testing.assert_allclose(
                float(np.asarray(summary.mean_r2)), want["mean_r2"],
                rtol=RTOL, atol=ATOL, err_msg=f"R2 {model.name}/{sub_name}",
            )
            np.testing.assert_allclose(
                float(np.asarray(summary.mean_n)), want["mean_n"],
                rtol=RTOL, atol=ATOL, err_msg=f"N {model.name}/{sub_name}",
            )
            checked += 1
    assert checked == 9, f"only {checked}/9 model x subset cells compared"
    assert nan_cells == 0, (
        f"{nan_cells} slope cells were NaN-skipped; the fixture must "
        "exercise every coefficient comparison"
    )
