"""Real-data parity gate (round-1 VERDICT item 5).

When the five real WRDS cache parquet files are present in the configured
RAW_DATA_DIR (and not synthetic-backed), build Table 1 from them and assert
every computed cell against the published Lewellen oracle
(``src/test_calc_Lewellen_2014.py:49-66``). Skips — with a reason — in
environments without WRDS access, so one populated cache directory is all
that stands between a fresh clone and a pass/fail parity verdict.

Also asserts hermetically (no real data needed) that the parity plumbing —
label map, task wiring — stays sound.
"""

import pytest

from fm_returnprediction_tpu.panel.characteristics import FACTORS_DICT
from fm_returnprediction_tpu.reporting.published import (
    PARITY_LABEL_MAP,
    published_table_1,
    real_cache_present,
)


@pytest.mark.skipif(
    not real_cache_present(),
    reason="real WRDS cache parquet files not present in RAW_DATA_DIR",
)
def test_table1_parity_against_published():
    from fm_returnprediction_tpu.reporting.published import run_parity_check

    diff = run_parity_check(strict=False)
    bad = diff[~diff["ok"]]
    assert bad.empty, f"parity failed on {len(bad)} cells:\n{bad.to_string(index=False)}"


def test_parity_label_map_covers_every_computed_row():
    """The canonical map must translate every pipeline display name (the 15
    reference-scope variables plus the opt-in turnover) to a distinct
    published row, covering the full published oracle."""
    from fm_returnprediction_tpu.panel.characteristics import TURNOVER_LABEL

    oracle_rows = set(published_table_1(computed_only=False).index)
    assert set(PARITY_LABEL_MAP.keys()) == set(FACTORS_DICT.keys()) | {
        TURNOVER_LABEL
    }
    assert set(PARITY_LABEL_MAP.values()) == oracle_rows
    assert len(set(PARITY_LABEL_MAP.values())) == len(PARITY_LABEL_MAP)


def test_parity_task_registered_for_wrds_backend(tmp_path):
    from fm_returnprediction_tpu.taskgraph.tasks import build_tasks

    kw = dict(raw_dir=tmp_path / "raw", processed_dir=tmp_path / "p",
              output_dir=tmp_path / "out")
    wrds_names = [t.name for t in build_tasks(synthetic=False, **kw)]
    synth_names = [t.name for t in build_tasks(synthetic=True, **kw)]
    assert "parity" in wrds_names
    assert "parity" not in synth_names
