"""Rolling E[r] forecast + decile sorts vs a numpy oracle.

The oracle transcribes the intended semantics independently (per-month numpy
lstsq, pandas rolling-mean-then-shift of coefficient rows, linear-interp
percentile breakpoints, strictly-below counting) so the batched JAX program
is pinned step by step, plus a statistical end-to-end check that a real
signal produces a positive 10−1 spread.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.models.forecast import (
    decile_sorts,
    rolling_er_forecast,
)


def _make_panel(rng, t=160, n=90, p=3, signal=0.05):
    x = rng.standard_normal((t, n, p))
    beta = signal * np.array([1.0, -0.5, 0.25])[:p]
    y = x @ beta + 0.02 * rng.standard_normal((t, n))
    mask = rng.random((t, n)) > 0.1
    y = np.where(mask, y, np.nan)
    x = np.where(mask[..., None], x, np.nan)
    return y, x, mask


def _oracle_forecast(y, x, mask, window, min_periods):
    t, n, p = x.shape
    coefs = np.full((t, p + 1), np.nan)
    month_valid = np.zeros(t, dtype=bool)
    for tt in range(t):
        ok = mask[tt] & np.isfinite(y[tt]) & np.all(np.isfinite(x[tt]), axis=1)
        if ok.sum() >= p + 1:  # reference gate: n >= design columns
            design = np.column_stack([np.ones(ok.sum()), x[tt][ok]])
            coef, *_ = np.linalg.lstsq(design, y[tt][ok], rcond=None)
            coefs[tt] = coef
            month_valid[tt] = True
    # rolling over surviving rows, shifted one row
    surv = pd.DataFrame(coefs[month_valid])
    bar = surv.rolling(window, min_periods=min_periods).mean().shift(1).to_numpy()
    full = np.full((t, p + 1), np.nan)
    full[np.where(month_valid)[0]] = bar

    rows = mask & np.isfinite(y) & np.all(np.isfinite(x), axis=2)
    have = np.all(np.isfinite(full), axis=1)
    er = np.full((t, n), np.nan)
    for tt in range(t):
        if have[tt]:
            er[tt, rows[tt]] = full[tt, 0] + x[tt, rows[tt]] @ full[tt, 1:]
    return er, rows & have[:, None]


def _oracle_deciles(er, ok, realized, n_deciles, min_obs):
    t, n = er.shape
    dec_ret = np.full((t, n_deciles), np.nan)
    month_valid = np.zeros(t, dtype=bool)
    for tt in range(t):
        o = ok[tt] & np.isfinite(realized[tt])
        if o.sum() < min_obs:
            continue
        month_valid[tt] = True
        vals = er[tt][o]
        breaks = np.percentile(vals, 100 * np.arange(1, n_deciles) / n_deciles)
        dec = (vals[:, None] > breaks[None, :]).sum(axis=1)
        r = realized[tt][o]
        for d in range(n_deciles):
            sel = dec == d
            if sel.any():
                dec_ret[tt, d] = r[sel].mean()
    return dec_ret, month_valid


@pytest.fixture(scope="module")
def forecast_case():
    rng = np.random.default_rng(41)
    y, x, mask = _make_panel(rng)
    window, min_periods = 60, 30
    fr = rolling_er_forecast(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask),
        window=window, min_periods=min_periods,
    )
    er_o, ok_o = _oracle_forecast(y, x, mask, window, min_periods)
    return y, x, mask, fr, er_o, ok_o


def test_forecast_matches_oracle(forecast_case):
    _, _, _, fr, er_o, ok_o = forecast_case
    np.testing.assert_array_equal(np.asarray(fr.er_valid), ok_o)
    np.testing.assert_allclose(
        np.asarray(fr.er), er_o, rtol=1e-8, atol=1e-10, equal_nan=True
    )


def test_forecast_is_strictly_out_of_sample(forecast_case):
    """Coefficients used at month t must not depend on month t's data:
    perturbing month t's returns must leave Ê[r]_t unchanged."""
    y, x, mask, fr, _, _ = forecast_case
    t_probe = 120
    y2 = y.copy()
    y2[t_probe] = np.where(mask[t_probe], 99.0, np.nan)
    fr2 = rolling_er_forecast(
        jnp.asarray(y2), jnp.asarray(x), jnp.asarray(mask),
        window=60, min_periods=30,
    )
    np.testing.assert_allclose(
        np.asarray(fr2.er)[t_probe], np.asarray(fr.er)[t_probe],
        rtol=1e-12, equal_nan=True,
    )


def test_decile_sorts_match_oracle(forecast_case):
    y, _, _, fr, er_o, ok_o = forecast_case
    res = decile_sorts(fr.er, fr.er_valid, jnp.asarray(y), min_obs=30)
    dec_o, mv_o = _oracle_deciles(er_o, ok_o, y, 10, 30)
    np.testing.assert_array_equal(np.asarray(res.month_valid), mv_o)
    np.testing.assert_allclose(
        np.asarray(res.decile_returns), dec_o, rtol=1e-8, atol=1e-10,
        equal_nan=True,
    )


def test_signal_produces_positive_spread(forecast_case):
    """x genuinely predicts y, so sorting on Ê[r] must produce a strongly
    positive, significant 10−1 spread and monotone-ish decile means."""
    y, _, _, fr, _, _ = forecast_case
    res = decile_sorts(fr.er, fr.er_valid, jnp.asarray(y), min_obs=30)
    spread = float(res.spread)
    t = float(res.spread_tstat)
    assert spread > 0.02, spread
    assert t > 5.0, t
    means = np.asarray(res.mean_returns)
    assert means[-1] > means[0]


def test_no_signal_no_spread():
    rng = np.random.default_rng(7)
    y, x, mask = _make_panel(rng, signal=0.0)
    fr = rolling_er_forecast(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask),
        window=60, min_periods=30,
    )
    res = decile_sorts(fr.er, fr.er_valid, jnp.asarray(y), min_obs=30)
    assert abs(float(res.spread_tstat)) < 4.0


def test_build_decile_table_on_synthetic_pipeline():
    """The pipeline-level decile table has the documented layout and finite
    spread stats on the synthetic universe."""
    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.panel.subsets import SUBSET_ORDER, compute_subset_masks
    from fm_returnprediction_tpu.pipeline import build_panel
    from fm_returnprediction_tpu.reporting.deciles import build_decile_table

    data = generate_synthetic_wrds(SyntheticConfig(n_firms=60, n_months=120))
    panel, _ = build_panel(data)
    masks = compute_subset_masks(panel)
    table = build_decile_table(
        panel, masks, window=24, min_periods=12, n_deciles=5, min_obs=10
    )
    assert list(table.columns) == SUBSET_ORDER
    assert list(table.index[:2]) == ["Decile 1", "Decile 2"]
    assert "10-1 spread" in table.index and "t(spread)" in table.index
    assert np.isfinite(table.loc["10-1 spread", "All stocks"])
    assert table.loc["Months", "All stocks"] > 0
