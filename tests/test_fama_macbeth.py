"""JAX econometrics core vs the numpy/pandas oracle.

The oracle (tests/oracle.py) transcribes the reference's formulas; these
tests assert the batched masked JAX kernels reproduce them to float64
round-off on ragged synthetic panels — far inside the 1e-4 parity budget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
from fm_returnprediction_tpu.ops.newey_west import compact_front, nw_mean_se
from fm_returnprediction_tpu.ops.ols import monthly_cs_ols
from fm_returnprediction_tpu.panel.dense import dense_to_long, long_to_dense

from oracle import (
    make_synthetic_long_panel,
    oracle_fama_macbeth_summary,
    oracle_monthly_cs_ols,
    oracle_nw_mean_se,
)


@pytest.fixture(scope="module")
def panel_and_oracle():
    rng = np.random.default_rng(7)
    df, pred_cols = make_synthetic_long_panel(rng)
    dense = long_to_dense(df, "mthcaldt", "permno", ["retx"] + pred_cols)
    oracle_cs = oracle_monthly_cs_ols(df, "retx", pred_cols)
    return df, pred_cols, dense, oracle_cs


def _run_jax(dense, pred_cols):
    y = jnp.asarray(dense.var("retx"))
    x = jnp.asarray(dense.select(pred_cols))
    mask = jnp.asarray(dense.mask)
    return fama_macbeth(y, x, mask)


def test_dense_roundtrip(panel_and_oracle):
    df, pred_cols, dense, _ = panel_and_oracle
    back = dense_to_long(dense)
    merged = back.rename(columns={"date": "mthcaldt", "id": "permno"})
    a = merged.sort_values(["permno", "mthcaldt"]).reset_index(drop=True)
    b = df.sort_values(["permno", "mthcaldt"]).reset_index(drop=True)
    assert len(a) == len(b)
    np.testing.assert_allclose(
        a[["retx"] + pred_cols].to_numpy(), b[["retx"] + pred_cols].to_numpy()
    )


def test_monthly_ols_matches_oracle(panel_and_oracle):
    _, pred_cols, dense, oracle_cs = panel_and_oracle
    cs, _ = _run_jax(dense, pred_cols)

    months = pd.DatetimeIndex(dense.months)
    valid = np.asarray(cs.month_valid)
    ran_months = months[valid]
    assert list(ran_months) == list(oracle_cs["mthcaldt"])

    np.testing.assert_allclose(
        np.asarray(cs.n_obs)[valid], oracle_cs["N"].to_numpy()
    )
    np.testing.assert_allclose(
        np.asarray(cs.r2)[valid], oracle_cs["R2"].to_numpy(), rtol=1e-9, atol=1e-12
    )
    want = oracle_cs[[f"slope_{c}" for c in pred_cols]].to_numpy()
    np.testing.assert_allclose(
        np.asarray(cs.slopes)[valid], want, rtol=1e-8, atol=1e-11
    )


def test_fm_summary_matches_oracle(panel_and_oracle):
    _, pred_cols, dense, oracle_cs = panel_and_oracle
    _, fm = _run_jax(dense, pred_cols)
    want = oracle_fama_macbeth_summary(oracle_cs, pred_cols)

    got_coef = np.asarray(fm.coef)
    got_t = np.asarray(fm.tstat)
    for i, col in enumerate(pred_cols):
        np.testing.assert_allclose(got_coef[i], want[f"{col}_coef"], rtol=1e-9)
        np.testing.assert_allclose(got_t[i], want[f"{col}_tstat"], rtol=1e-9)
    np.testing.assert_allclose(float(fm.mean_r2), want["mean_R2"], rtol=1e-10)
    np.testing.assert_allclose(float(fm.mean_n), want["mean_N"], rtol=1e-12)


def test_nw_se_matches_oracle(rng):
    x = rng.normal(size=200).cumsum() * 0.1 + rng.normal(size=200)
    got = nw_mean_se(jnp.asarray(x), jnp.ones(200, bool))
    np.testing.assert_allclose(float(got), oracle_nw_mean_se(x), rtol=1e-12)


def test_nw_se_gapped_series_uses_compacted_lags(rng):
    """Lag-k autocovariance must pair adjacent SURVIVING entries, matching
    pandas .dropna() semantics in the reference (src/regressions.py:113)."""
    x = rng.normal(size=120)
    valid = rng.random(120) > 0.3
    got = nw_mean_se(jnp.asarray(x), jnp.asarray(valid))
    np.testing.assert_allclose(float(got), oracle_nw_mean_se(x[valid]), rtol=1e-12)


def test_nw_se_short_series_nan():
    assert np.isnan(float(nw_mean_se(jnp.ones(5), jnp.arange(5) < 1)))


def test_nw_textbook_weight_differs(rng):
    x = rng.normal(size=80).cumsum()
    ref = float(nw_mean_se(jnp.asarray(x), jnp.ones(80, bool), weight="reference"))
    txt = float(nw_mean_se(jnp.asarray(x), jnp.ones(80, bool), weight="textbook"))
    assert ref != pytest.approx(txt)


def test_compact_front():
    x = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    valid = jnp.asarray([False, True, False, True])
    xc, n = compact_front(x, valid)
    assert int(n) == 2
    np.testing.assert_array_equal(np.asarray(xc), [2.0, 4.0, 0.0, 0.0])


def test_min_months_rule():
    """Predictors with <10 valid months report NaN coef/tstat
    (src/regressions.py:114-117)."""
    rng = np.random.default_rng(3)
    T, N, P = 8, 30, 2  # only 8 months -> below the 10-month floor
    y = jnp.asarray(rng.normal(size=(T, N)))
    x = jnp.asarray(rng.normal(size=(T, N, P)))
    mask = jnp.ones((T, N), bool)
    _, fm = fama_macbeth(y, x, mask)
    assert np.all(np.isnan(np.asarray(fm.coef)))
    assert np.all(np.isnan(np.asarray(fm.tstat)))
    assert int(fm.n_months) == T


def test_skip_month_with_too_few_rows():
    """A month with fewer than P+1 complete-case rows must not run
    (src/regressions.py:52)."""
    rng = np.random.default_rng(4)
    T, N, P = 12, 20, 3
    y = rng.normal(size=(T, N))
    x = rng.normal(size=(T, N, P))
    mask = np.ones((T, N), bool)
    mask[5, 3:] = False  # month 5 has 3 rows < P+1 = 4
    cs = monthly_cs_ols(jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask))
    valid = np.asarray(cs.month_valid)
    assert not valid[5] and valid.sum() == T - 1


def test_jit_and_f32_path():
    """The kernel must be jittable and run in float32 (TPU path)."""
    rng = np.random.default_rng(5)
    T, N, P = 24, 50, 3
    y = jnp.asarray(rng.normal(size=(T, N)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(T, N, P)), dtype=jnp.float32)
    mask = jnp.ones((T, N), bool)
    cs, fm = jax.jit(fama_macbeth)(y, x, mask)
    assert cs.slopes.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(fm.coef)))


def test_singular_month_matches_pinv_not_nan():
    """A month with a constant predictor (collinear with the intercept) must
    produce the statsmodels/pinv minimum-norm solution, not NaNs that poison
    mean_R2 (reference runs such months through sm.OLS's pinv)."""
    rng = np.random.default_rng(9)
    T, N, P = 12, 30, 2
    y = rng.normal(size=(T, N))
    x = rng.normal(size=(T, N, P))
    x[4, :, 1] = 1.0  # constant across the cross-section in month 4
    cs, fm = fama_macbeth(jnp.asarray(y), jnp.asarray(x), jnp.ones((T, N), bool))
    assert bool(cs.month_valid[4])
    assert np.isfinite(np.asarray(cs.slopes[4])).all()
    assert np.isfinite(float(fm.mean_r2))
    # pinv ground truth for that month
    xa = np.column_stack([np.ones(N), x[4]])
    want = np.linalg.pinv(xa) @ y[4]
    np.testing.assert_allclose(np.asarray(cs.slopes[4]), want[1:], atol=1e-8)
