"""Multi-chip layer on the virtual 8-device CPU mesh (SURVEY §4d).

Asserts the firm-sharded Gram-psum FM path reproduces the single-chip
batched solver / numpy oracle, that padding slots are exact no-ops, and that
the replicate-sharded bootstrap is key-deterministic and statistically sane.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
from fm_returnprediction_tpu.parallel import (
    block_bootstrap_se,
    bootstrap_replicate_means,
    fama_macbeth_sharded,
    make_mesh,
    pad_to_multiple,
    shard_panel,
)
from fm_returnprediction_tpu.panel.dense import long_to_dense

from oracle import (
    make_synthetic_long_panel,
    oracle_fama_macbeth_summary,
    oracle_monthly_cs_ols,
)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh(axis_name="firms")


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(31)
    df, pred_cols = make_synthetic_long_panel(rng)
    dense = long_to_dense(df, "mthcaldt", "permno", ["retx"] + pred_cols)
    y = jnp.asarray(dense.var("retx"))
    x = jnp.asarray(dense.select(pred_cols))
    mask = jnp.asarray(dense.mask)
    return df, pred_cols, dense, (y, x, mask)


def test_pad_to_multiple_shapes():
    a = jnp.ones((5, 13, 3))
    p = pad_to_multiple(a, axis=1, multiple=8, fill=0.0)
    assert p.shape == (5, 16, 3)
    np.testing.assert_array_equal(np.asarray(p[:, 13:, :]), 0.0)
    # already a multiple → unchanged object shape
    assert pad_to_multiple(a, axis=0, multiple=5).shape == (5, 13, 3)


def test_shard_panel_places_on_mesh(mesh, panel):
    _, _, _, (y, x, mask) = panel
    ys, xs, ms = shard_panel(y, x, mask, mesh)
    assert ys.shape[1] % 8 == 0 and ys.shape[1] >= y.shape[1]
    assert xs.shape[:2] == ys.shape and ms.shape == ys.shape
    # padded slots are masked out
    assert not np.asarray(ms)[:, y.shape[1]:].any()
    assert ys.sharding.spec[1] == "firms"
    assert xs.sharding.spec[1] == "firms"


def test_sharded_fm_matches_single_chip(mesh, panel):
    df, pred_cols, dense, (y, x, mask) = panel
    cs_s, fm_s = fama_macbeth_sharded(y, x, mask, mesh=mesh)
    cs_1, fm_1 = fama_macbeth(y, x, mask, solver="normal")

    np.testing.assert_array_equal(
        np.asarray(cs_s.month_valid), np.asarray(cs_1.month_valid)
    )
    valid = np.asarray(cs_1.month_valid)
    np.testing.assert_allclose(
        np.asarray(cs_s.slopes)[valid], np.asarray(cs_1.slopes)[valid],
        rtol=1e-7, atol=1e-10,
    )
    np.testing.assert_allclose(
        np.asarray(cs_s.r2)[valid], np.asarray(cs_1.r2)[valid],
        rtol=1e-7, atol=1e-10,
    )
    np.testing.assert_allclose(
        np.asarray(fm_s.coef), np.asarray(fm_1.coef), rtol=1e-7, atol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(fm_s.tstat), np.asarray(fm_1.tstat), rtol=1e-6, atol=1e-9
    )


def test_sharded_fm_matches_oracle(mesh, panel):
    df, pred_cols, dense, (y, x, mask) = panel
    _, fm_s = fama_macbeth_sharded(y, x, mask, mesh=mesh)
    oracle_cs = oracle_monthly_cs_ols(df, "retx", pred_cols)
    want = oracle_fama_macbeth_summary(oracle_cs, pred_cols)
    for i, col in enumerate(pred_cols):
        np.testing.assert_allclose(
            np.asarray(fm_s.coef)[i], want[f"{col}_coef"], rtol=1e-6, atol=1e-9
        )
        np.testing.assert_allclose(
            np.asarray(fm_s.tstat)[i], want[f"{col}_tstat"], rtol=1e-5, atol=1e-8
        )


def test_sharded_fm_subset_mesh(panel):
    """A 2-device sub-mesh gives identical answers (device-count invariance)."""
    _, _, _, (y, x, mask) = panel
    m8 = make_mesh(axis_name="firms")
    m2 = make_mesh(n_devices=2, axis_name="firms")
    _, fm8 = fama_macbeth_sharded(y, x, mask, mesh=m8)
    _, fm2 = fama_macbeth_sharded(y, x, mask, mesh=m2)
    np.testing.assert_allclose(
        np.asarray(fm8.coef), np.asarray(fm2.coef), rtol=1e-9, atol=1e-12
    )


def _toy_slopes(rng, t=240, p=3, rho=0.3):
    """AR(1) slope series with missing months, known mean."""
    eps = rng.standard_normal((t, p))
    s = np.zeros((t, p))
    for i in range(1, t):
        s[i] = rho * s[i - 1] + eps[i]
    valid = rng.random((t, p)) > 0.1
    return jnp.asarray(s), jnp.asarray(valid)


def test_bootstrap_deterministic_and_sharded_matches_spec():
    rng = np.random.default_rng(99)
    slopes, valid = _toy_slopes(rng)
    key = jax.random.key(0)
    r1 = block_bootstrap_se(slopes, valid, key, n_replicates=512)
    r2 = block_bootstrap_se(slopes, valid, key, n_replicates=512)
    np.testing.assert_array_equal(np.asarray(r1.se), np.asarray(r2.se))

    mesh = make_mesh(axis_name="boot")
    rs = block_bootstrap_se(slopes, valid, key, n_replicates=512, mesh=mesh)
    # Same keys, same replicate set → identical moments regardless of mesh.
    np.testing.assert_allclose(
        np.asarray(rs.se), np.asarray(r1.se), rtol=1e-8, atol=1e-12
    )
    assert rs.n_replicates == 512


def test_bootstrap_se_tracks_nw_scale():
    """Bootstrap SE should approximate the iid SE for white-noise slopes."""
    rng = np.random.default_rng(3)
    t = 600
    s = rng.standard_normal((t, 2))
    valid = jnp.ones((t, 2), dtype=bool)
    res = block_bootstrap_se(
        jnp.asarray(s), valid, jax.random.key(1), n_replicates=4000, block_length=5
    )
    iid_se = s.std(axis=0, ddof=1) / np.sqrt(t)
    np.testing.assert_allclose(np.asarray(res.se), iid_se, rtol=0.15)


def test_bootstrap_short_series_nan():
    slopes = jnp.asarray(np.random.default_rng(0).standard_normal((50, 2)))
    valid = jnp.zeros((50, 2), dtype=bool).at[0, 0].set(True)
    res = block_bootstrap_se(slopes, valid, jax.random.key(0), n_replicates=64)
    assert np.isnan(np.asarray(res.se)[0])  # 1 valid month → NaN
    assert np.isnan(np.asarray(res.se)[1])  # 0 valid months → NaN


def test_bootstrap_under_block_length_nan():
    """n_valid <= block_length has at most one distinct block start, so every
    replicate equals the sample mean — must report NaN, not SE~0 (ADVICE r1)."""
    rng = np.random.default_rng(3)
    slopes = jnp.asarray(rng.standard_normal((50, 2)))
    valid = jnp.zeros((50, 2), dtype=bool)
    valid = valid.at[:5, 0].set(True)   # n_valid == block_length (5)
    valid = valid.at[:6, 1].set(True)   # n_valid == block_length + 1
    res = block_bootstrap_se(slopes, valid, jax.random.key(0), n_replicates=64)
    se = np.asarray(res.se)
    assert np.isnan(se[0])
    assert np.isfinite(se[1]) and se[1] > 0.0


def test_bootstrap_rejects_degenerate_replicate_count():
    slopes = jnp.asarray(np.random.default_rng(0).standard_normal((50, 1)))
    valid = jnp.ones((50, 1), dtype=bool)
    with pytest.raises(ValueError, match="n_replicates"):
        block_bootstrap_se(slopes, valid, jax.random.key(0), n_replicates=1)


def test_bootstrap_f32_tiny_spread_not_zero():
    """f32 + near-constant slope series: the centered moment reduction must
    not cancel to SE=0 (the naive E[x2]-mean^2 form does)."""
    rng = np.random.default_rng(11)
    t = 400
    s = (0.05 + 1e-6 * rng.standard_normal((t, 1))).astype(np.float32)
    valid = jnp.ones((t, 1), dtype=bool)
    res = block_bootstrap_se(
        jnp.asarray(s), valid, jax.random.key(2), n_replicates=1000, block_length=5
    )
    se = float(np.asarray(res.se)[0])
    expect = float(s.std(ddof=1) / np.sqrt(t))  # iid scale for white noise
    assert se > 0.0
    assert 0.2 * expect < se < 5 * expect


def test_table2_mesh_matches_single_device():
    """build_table_2 with the mesh (Gram-psum FM) reproduces the
    single-device table within the parity budget."""
    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.pipeline import build_panel
    from fm_returnprediction_tpu.reporting.table2 import build_table_2

    data = generate_synthetic_wrds(SyntheticConfig(n_firms=50, n_months=80))
    panel, factors = build_panel(data)
    masks = compute_subset_masks(panel)
    t_single = build_table_2(panel, masks, factors)
    t_mesh = build_table_2(panel, masks, factors, mesh=make_mesh(axis_name="firms"))
    # formatted strings: identical at the displayed precision except for
    # rare last-digit rounding flips between the SVD and Gram routes
    a = t_single.to_numpy().astype(str).ravel()
    b = t_mesh.to_numpy().astype(str).ravel()
    agree = (a == b).mean()
    assert agree > 0.95, f"only {agree:.2%} of formatted cells agree"


def _near_singular_panel(t=24, n=48, p=6, cond=1e6, seed=5):
    """Months at the reference's n >= P+1 admission boundary with an
    ill-conditioned design: predictors are near-collinear (pairwise columns
    differ by ~1/cond perturbations), the regime ops/ols.py documents as
    drifting under the one-shot Gram route."""
    rng = np.random.default_rng(seed)
    base = rng.standard_normal((t, n, 1))
    x = np.repeat(base, p, axis=2)
    x += rng.standard_normal((t, n, p)) / cond
    beta = rng.standard_normal(p)
    y = x @ beta + 0.01 * rng.standard_normal((t, n))
    # only P+1 valid rows per month: square-ish, near-singular systems
    mask = np.zeros((t, n), dtype=bool)
    for i in range(t):
        mask[i, rng.choice(n, size=p + 1, replace=False)] = True
    y = np.where(mask, y, np.nan)
    return jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask)


def test_sharded_refinement_recovers_lstsq_on_near_singular_months():
    """VERDICT r1 item 6: measure the Gram-route drift on near-singular
    months and assert the sharded path's iterative refinement removes it.
    f64 here; the one-shot Gram solve must be visibly worse than the
    refined solve for the test to be meaningful."""
    from fm_returnprediction_tpu.ops.ols import monthly_cs_ols
    from fm_returnprediction_tpu.parallel.fm_sharded import monthly_cs_ols_sharded
    from fm_returnprediction_tpu.parallel.mesh import shard_panel

    y, x, mask = _near_singular_panel()
    cs_svd = monthly_cs_ols(y, x, mask, solver="lstsq")

    mesh = make_mesh(axis_name="firms")
    ys, xs, ms = shard_panel(y, x, mask, mesh)
    cs_raw = monthly_cs_ols_sharded(ys, xs, ms, mesh, n_refine=0)
    cs_ref = monthly_cs_ols_sharded(ys, xs, ms, mesh, n_refine=2)

    valid = np.asarray(cs_svd.month_valid)
    assert valid.any()
    want = np.asarray(cs_svd.slopes)[valid]

    def drift(cs):
        got = np.asarray(cs.slopes)[valid]
        scale = np.maximum(np.abs(want), 1.0)
        return np.max(np.abs(got - want) / scale)

    drift_raw, drift_ref = drift(cs_raw), drift(cs_ref)
    # refined path pinned to the SVD parity solution
    assert drift_ref < 1e-7, f"refined drift {drift_ref:.2e}"
    # and the measurement is meaningful: one-shot Gram genuinely drifts here
    assert drift_raw > 10 * max(drift_ref, 1e-12), (
        f"fixture not discriminating: raw {drift_raw:.2e} vs refined {drift_ref:.2e}"
    )
    # r2 of refined path also matches lstsq
    np.testing.assert_allclose(
        np.asarray(cs_ref.r2)[valid], np.asarray(cs_svd.r2)[valid],
        rtol=1e-6, atol=1e-8,
    )


def test_sharded_tsqr_compressed_regime_near_singular():
    """The QR-compression branch (local rows > Q+1, so the raw-stack exact
    path does NOT apply) on near-singular months: TSQR must stay well inside
    the 1e-4 parity budget vs single-chip lstsq (measured ~2e-6 at
    cond 1e6 in f64), while the one-shot Gram route drifts catastrophically."""
    from fm_returnprediction_tpu.ops.ols import monthly_cs_ols
    from fm_returnprediction_tpu.parallel.fm_sharded import monthly_cs_ols_sharded
    from fm_returnprediction_tpu.parallel.mesh import shard_panel

    rng = np.random.default_rng(5)
    t, n, p, cond = 12, 512, 6, 1e6
    base = rng.standard_normal((t, n, 1))
    x = np.repeat(base, p, axis=2) + rng.standard_normal((t, n, p)) / cond
    beta = rng.standard_normal(p)
    y = x @ beta + 0.01 * rng.standard_normal((t, n))
    mask = np.zeros((t, n), dtype=bool)
    for i in range(t):
        mask[i, rng.choice(n, size=p + 1, replace=False)] = True
    y = jnp.asarray(np.where(mask, y, np.nan))
    x, mask = jnp.asarray(x), jnp.asarray(mask)

    cs_svd = monthly_cs_ols(y, x, mask, solver="lstsq")
    mesh = make_mesh(axis_name="firms")
    ys, xs, ms = shard_panel(y, x, mask, mesh)
    n_local = ys.shape[1] // mesh.shape["firms"]
    assert n_local > p + 2, "fixture must exercise the QR branch"
    cs = monthly_cs_ols_sharded(ys, xs, ms, mesh)

    valid = np.asarray(cs_svd.month_valid)
    want = np.asarray(cs_svd.slopes)[valid]
    got = np.asarray(cs.slopes)[valid]
    drift = np.max(np.abs(got - want) / np.maximum(np.abs(want), 1.0))
    assert drift < 5e-5, f"compressed TSQR drift {drift:.2e}"


def test_build_panel_mesh_daily_stage_matches_single_device():
    """get_factors routes the daily stage through the firm-sharded kernels
    when a mesh is passed; vol/beta columns must match the single-device
    (chunked) path exactly — the sharded program is collective-free."""
    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.pipeline import build_panel

    data = generate_synthetic_wrds(SyntheticConfig(n_firms=30, n_months=40))
    p_single, _ = build_panel(data)
    p_mesh, _ = build_panel(data, mesh=make_mesh(axis_name="firms"))
    for col in ("rolling_std_252", "beta"):
        a = p_single.var(col)
        b = p_mesh.var(col)
        np.testing.assert_array_equal(a, b, err_msg=col)


def test_default_mesh_honors_setting(monkeypatch):
    from fm_returnprediction_tpu.parallel import default_mesh

    # settings snapshot MESH_DEVICES at import; patch the dict directly
    from fm_returnprediction_tpu import settings

    monkeypatch.setitem(settings.d, "MESH_DEVICES", 0)
    m = default_mesh()
    assert m is not None and m.size == len(jax.devices())
    monkeypatch.setitem(settings.d, "MESH_DEVICES", 4)
    assert default_mesh().size == 4
    monkeypatch.setitem(settings.d, "MESH_DEVICES", 1)
    assert default_mesh() is None
