"""Perf-regression sentinel (`telemetry.regress`).

Fast unit tests pin the detector's semantics on synthetic histories; the
tier-2 gate (marked ``slow``) runs the sentinel over the REAL in-repo
``BENCH_r*.json`` trajectory — improvements must read as improvements,
nothing may falsely regress, and an injected synthetic regression must be
caught. The gate skips cleanly when the history is absent (a fresh clone
without bench artifacts)."""

import glob
import json
import os
from pathlib import Path

import pytest

from fm_returnprediction_tpu.telemetry import regress

pytestmark = pytest.mark.obs

REPO = Path(__file__).resolve().parent.parent


def _write_round(tmp_path, n, metric, value, extra=None, name=None):
    doc = {
        "n": n,
        "parsed": {
            "metric": metric,
            "value": value,
            "unit": "s",
            "vs_baseline": 1.0,
            "extra": extra or {},
        },
    }
    path = tmp_path / (name or f"BENCH_r{n:02d}.json")
    path.write_text(json.dumps(doc))
    return path


# -- parsing / ordering -----------------------------------------------------


def test_load_rounds_orders_by_n_then_filename(tmp_path):
    p2 = _write_round(tmp_path, 2, "wall_s", 2.0)
    p1 = _write_round(tmp_path, 1, "wall_s", 1.0)
    # a self-run artifact without "n" falls back to the rNN in its name
    doc = {"parsed": {"metric": "wall_s", "value": 1.5, "extra": {}}}
    (tmp_path / "BENCH_r01_self.json").write_text(json.dumps(doc))
    rounds = regress.load_rounds(
        [p2, p1, tmp_path / "BENCH_r01_self.json"]
    )
    assert [r.label for r in rounds] == [
        "BENCH_r01", "BENCH_r01_self", "BENCH_r02"
    ]


def test_load_round_tolerates_foreign_files(tmp_path):
    (tmp_path / "junk.json").write_text("not json at all")
    (tmp_path / "other.json").write_text('{"hello": 1}')
    assert regress.load_round(tmp_path / "junk.json") is None
    assert regress.load_round(tmp_path / "other.json") is None
    assert regress.load_round(tmp_path / "missing.json") is None


def test_flatten_skips_bools_nulls_and_skip_markers(tmp_path):
    p1 = _write_round(tmp_path, 1, "wall_s", 1.0, extra={
        "warm_s": 2.0,
        "flag": True,
        "probe": None,
        "pallas_ms": {"skipped": "tpu-only"},
        "stages": {"a": 0.5},
    })
    r = regress.load_round(p1)
    assert r.values == {"warm_s": 2.0, "stages.a": 0.5, "wall_s": 1.0}


# -- direction classification ----------------------------------------------


@pytest.mark.parametrize("key,expected", [
    ("pipeline_warm_s", "lower"),
    ("serving_p99_ms", "lower"),
    ("specgrid_gram_mb", "lower"),
    ("guard_overhead_table2_pct", "lower"),
    ("serving_qps", "higher"),
    ("specgrid_speedup_warm", "higher"),
    ("daily_fullscale_rows_per_s", "higher"),
    ("vs_baseline", "higher"),
    ("specgrid_programs", None),
    ("jax_cache_before.entries", None),
    ("real_pipeline_stage_s.table_2", None),  # attribution, not gated
    ("serving_ledger_compile_s", None),  # cache-state dependent, not gated
])
def test_direction(key, expected):
    assert regress.direction(key) == expected


# -- verdict semantics ------------------------------------------------------


def _analyze(tmp_path, histories):
    """histories: {metric: [v1, v2, ...]} — one file per round index."""
    n_rounds = max(len(v) for v in histories.values())
    paths = []
    for i in range(n_rounds):
        extra = {
            k: vals[i] for k, vals in histories.items()
            if i < len(vals) and vals[i] is not None
        }
        paths.append(
            _write_round(tmp_path, i + 1, "headline_s",
                         extra.pop("headline_s", 1.0), extra=extra)
        )
    return regress.analyze(regress.load_rounds(paths))


def test_new_best_is_improved_and_regression_is_caught(tmp_path):
    report = _analyze(tmp_path, {
        "headline_s": [10.0, 5.0, 4.0],       # improving
        "warm_s": [10.0, 4.0, 13.0],          # 3.25x worse than best
        "steady_s": [1.0, 1.05, 1.1],         # within the 25% floor band
    })
    by_key = {v.key: v for v in report.verdicts}
    assert by_key["headline_s"].status == "improved"
    assert by_key["warm_s"].status == "regressed"
    assert by_key["steady_s"].status == "ok"
    assert not report.ok
    assert [v.key for v in report.regressions] == ["warm_s"]


def test_higher_is_better_directions(tmp_path):
    report = _analyze(tmp_path, {
        "serving_qps": [100.0, 150.0, 80.0],   # collapsed beyond band
        "x_speedup": [2.0, 2.1, 2.2],          # new best
    })
    by_key = {v.key: v for v in report.verdicts}
    assert by_key["serving_qps"].status == "regressed"
    assert by_key["x_speedup"].status == "improved"


def test_fitted_noise_band_widens_for_flappy_metrics(tmp_path):
    # history flaps ±60%: the fitted band must absorb another 60% swing
    # that the 25% floor alone would have flagged
    report = _analyze(tmp_path, {
        "flappy_s": [1.0, 1.6, 1.0, 1.6, 1.0, 1.55],
    })
    (v,) = [v for v in report.verdicts if v.key == "flappy_s"]
    assert v.status == "ok"
    assert v.band_ratio > 1.25


def test_abs_floor_suppresses_microscopic_regressions(tmp_path):
    report = _analyze(tmp_path, {
        "tiny_s": [0.001, 0.001, 0.002],  # 2x but 1ms — below abs floor
    })
    (v,) = [v for v in report.verdicts if v.key == "tiny_s"]
    assert v.status == "ok"


def test_new_missing_and_nonpositive_statuses(tmp_path):
    report = _analyze(tmp_path, {
        "old_s": [1.0, 1.0, None],        # gone in latest
        "fresh_s": [None, None, 1.0],     # first appearance
        "signed_pct": [-3.0, 2.0, 5.0],   # non-positive history
    })
    by_key = {v.key: v for v in report.verdicts}
    assert by_key["old_s"].status == "missing"
    assert by_key["fresh_s"].status == "new"
    assert by_key["signed_pct"].status == "skipped"
    assert report.ok  # none of those gate


def test_report_roundtrips_to_json(tmp_path):
    report = _analyze(tmp_path, {"headline_s": [2.0, 1.0, 3.0]})
    doc = report.to_json()
    assert doc["ok"] is False
    assert doc["latest"] == "BENCH_r03"
    text = report.format_text()
    assert "FAIL" in text and "headline_s" in text
    json.dumps(doc)  # serializable


# -- CLI --------------------------------------------------------------------


def test_cli_gates_and_no_fail_mode(tmp_path, capsys, monkeypatch):
    _write_round(tmp_path, 1, "wall_s", 1.0)
    _write_round(tmp_path, 2, "wall_s", 5.0)
    files = sorted(str(p) for p in tmp_path.glob("BENCH_*.json"))
    assert regress.main(files) == 1  # regression → gate fails
    assert regress.main([*files, "--no-fail"]) == 0
    out = capsys.readouterr().out
    assert "regressed" in out
    rc = regress.main([*files, "--json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False


def test_cli_too_little_history_exits_zero(tmp_path, capsys):
    p = _write_round(tmp_path, 1, "wall_s", 1.0)
    assert regress.main([str(p)]) == 0
    assert "need >=2" in capsys.readouterr().err


# -- tier-2 gate over the real in-repo history ------------------------------


def _repo_history():
    return sorted(glob.glob(str(REPO / "BENCH_r*.json")))


@pytest.mark.slow
def test_repo_bench_history_has_no_false_regressions():
    files = _repo_history()
    if len(files) < 2:
        pytest.skip("no BENCH_*.json history in this checkout")
    report = regress.analyze(regress.load_rounds(files))
    assert report.ok, report.format_text()
    # the known r03→r05 improvement trajectory reads as improvement
    # (keys carry the @shape@device qualifiers since PR 11 — group by
    # the bare metric; rounds predating the disclosures leave a legacy
    # unqualified series whose latest value is legitimately "missing")
    by_key: dict = {}
    for v in report.verdicts:
        by_key.setdefault(v.key.split("@", 1)[0], set()).add(v.status)
    if "real_pipeline_warm_s" in by_key:
        assert by_key["real_pipeline_warm_s"] & {"improved", "ok"}


@pytest.mark.slow
def test_repo_history_catches_injected_regression(tmp_path):
    files = _repo_history()
    if len(files) < 2:
        pytest.skip("no BENCH_*.json history in this checkout")
    rounds = regress.load_rounds(files)
    latest = json.loads(Path(files[-1]).read_text())
    payload = latest.get("parsed", latest)
    payload["value"] = payload["value"] * 3
    for key in ("real_pipeline_warm_s", "pipeline_warm_s"):
        if key in (payload.get("extra") or {}):
            payload["extra"][key] *= 3
    latest["n"] = max(r.order[0] for r in rounds) + 1
    inject = tmp_path / "BENCH_r99.json"
    inject.write_text(json.dumps(latest))
    report = regress.analyze(regress.load_rounds([*files, inject]))
    assert not report.ok
    # series keys are @shape@device-qualified since PR 11; the injected
    # headline must be caught under its bare metric name (exact-key
    # equality silently never matched once the qualifiers landed)
    assert any(
        v.key.split("@", 1)[0] == payload["metric"]
        for v in report.regressions
    ), report.format_text()
