"""Time-sharded (sequence-parallel) rolling kernels vs the single-device
``ops.rolling`` oracles: exact window semantics across shard boundaries,
the ppermute halo actually present in the compiled program, ragged-length
padding, and the single-hop window constraint."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_returnprediction_tpu.ops.rolling import (
    rolling_mean,
    rolling_std,
    rolling_sum,
)
from fm_returnprediction_tpu.parallel import make_mesh
from fm_returnprediction_tpu.parallel.time_sharded import (
    _jitted_rolling,
    rolling_mean_time_sharded,
    rolling_moments_time_sharded,
    rolling_std_time_sharded,
    rolling_sum_time_sharded,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(31)
    d, n = 160, 24  # 8 shards x 20 rows; window 16 crosses every boundary
    x = rng.standard_normal((d, n))
    x[rng.random((d, n)) < 0.12] = np.nan
    return x


def _mesh():
    return make_mesh(axis_name="time")


def test_matches_single_device_sum_and_std(data):
    mesh = _mesh()
    for mp in (1, 5, 16):
        want = np.asarray(rolling_sum(jnp.asarray(data), 16, mp))
        got = np.asarray(rolling_sum_time_sharded(data, 16, mp, mesh=mesh))
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12,
                                   equal_nan=True)
        want = np.asarray(rolling_std(jnp.asarray(data), 16, mp))
        got = np.asarray(rolling_std_time_sharded(data, 16, mp, mesh=mesh))
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12,
                                   equal_nan=True)
        want = np.asarray(rolling_mean(jnp.asarray(data), 16, mp))
        got = np.asarray(rolling_mean_time_sharded(data, 16, mp, mesh=mesh))
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12,
                                   equal_nan=True)


def test_moments_and_sharding(data):
    mesh = _mesh()
    s1, s2, cnt = rolling_moments_time_sharded(data, 16, mesh=mesh)
    assert s1.sharding.spec[0] == "time"
    finite = np.isfinite(data)
    xz = np.where(finite, data, 0.0)
    # independent numpy oracle for the windowed count and sum at a boundary
    # row (row 20 = first row of shard 1: its window spans the shard seam)
    row = 20
    lo = max(0, row - 15)
    np.testing.assert_allclose(
        np.asarray(cnt)[row], finite[lo:row + 1].sum(axis=0), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(s1)[row], xz[lo:row + 1].sum(axis=0), rtol=1e-12, atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(s2)[row], (xz[lo:row + 1] ** 2).sum(axis=0),
        rtol=1e-12, atol=1e-12,
    )


def test_ragged_length_pads_and_trims(data):
    mesh = _mesh()
    ragged = data[:150]  # 150 % 8 != 0 → NaN-padded to 152? (19*8) then trimmed
    want = np.asarray(rolling_std(jnp.asarray(ragged), 12, 4))
    got = np.asarray(rolling_std_time_sharded(ragged, 12, 4, mesh=mesh))
    assert got.shape == ragged.shape
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_window_must_fit_one_shard(data):
    with pytest.raises(ValueError, match="halo"):
        rolling_std_time_sharded(data, 24, 4, mesh=_mesh())  # 24 > 160/8


def test_weekly_beta_matches_single_device(data):
    """Time-sharded weekly beta vs the single-device kernel: week segments
    straddling shard seams, NaN returns AND NaN market days, ragged D."""
    from fm_returnprediction_tpu.ops.daily_kernels import (
        weekly_rolling_beta_monthly,
    )
    from fm_returnprediction_tpu.parallel.time_sharded import (
        weekly_rolling_beta_time_sharded,
    )

    rng = np.random.default_rng(77)
    for d_days in (400, 397):  # multiple of 8, and ragged
        n, n_months, n_weeks = 12, 19, 60
        ret = 0.02 * rng.standard_normal((d_days, n))
        ret[rng.random((d_days, n)) < 0.05] = np.nan
        mask = rng.random((d_days, n)) > 0.15
        mkt = 0.01 * rng.standard_normal(d_days)
        mkt[rng.random(d_days) < 0.04] = np.nan
        week_id = np.minimum(np.arange(d_days) // 7, n_weeks - 1)
        week_month_id = np.minimum(np.arange(n_weeks) * 7 // 21, n_months - 1)

        want = np.asarray(weekly_rolling_beta_monthly(
            jnp.asarray(ret), jnp.asarray(mask), jnp.asarray(mkt),
            jnp.asarray(week_id), n_weeks, jnp.asarray(week_month_id),
            n_months, window_weeks=12,
        ))
        got = np.asarray(weekly_rolling_beta_time_sharded(
            ret, mask, mkt, week_id, n_weeks, week_month_id, n_months,
            window_weeks=12, mesh=make_mesh(axis_name="time"),
        ))
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12,
                                   equal_nan=True)


def test_compiled_program_contains_the_halo_permute(data):
    """The sequence-parallel exchange must be REAL: the partitioned program
    contains a collective-permute (the halo) and an all-gather (the prefix
    offsets) — the inverse of the firm-sharded daily kernels' zero-collective
    assertion."""
    mesh = _mesh()
    run = _jitted_rolling(mesh, "time", 16, "std", 4)
    from jax.sharding import NamedSharding, PartitionSpec as P

    arg = jax.ShapeDtypeStruct((160, 24), jnp.float64,
                               sharding=NamedSharding(mesh, P("time", None)))
    hlo = run.lower(arg).compile().as_text()
    assert "collective-permute" in hlo, "halo exchange missing"
    assert "all-gather" in hlo or "all-reduce" in hlo, "prefix gather missing"
