"""REAL multi-process ``jax.distributed``: two local CPU processes.

Round-3 verdict item 5: ``initialize_multihost``'s ``jax.distributed`` path
had only ever run with one process. Here the parent spawns two fresh Python
processes (``tests/mp_worker.py``) that rendezvous on a local coordinator,
form the 4-device global topology (2 processes × 2 virtual CPU devices),
build the production months×firms mesh with one row per process, and run a
hierarchical Fama-MacBeth step whose collectives actually cross the process
boundary (Gloo transport) — asserting agreement with the single-device
solver inside each worker.
"""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

_WORKER = Path(__file__).parent / "mp_worker.py"
_TG_WORKER = Path(__file__).parent / "mp_taskgraph_worker.py"
_REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_worker_pair(worker: Path, extra_args, marker: str, budget_s: float):
    port, nprocs = _free_port(), 2
    env = {**os.environ, "PYTHONPATH": str(_REPO)}
    # the parent's pytest env must not leak its 8-device flag into workers
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(nprocs), str(port),
             *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(nprocs)
    ]
    outs = []
    deadline = time.monotonic() + budget_s  # shared: total wait, not per-worker
    try:
        for p in procs:
            out, _ = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
            outs.append(out)
    except subprocess.TimeoutExpired:
        pytest.fail("distributed workers hung:\n" + "\n---\n".join(outs))
    finally:
        for p in procs:  # never leak workers holding the coordinator port
            if p.poll() is None:
                p.kill()
    # Environment gap, not a code fault: this container's jaxlib CPU
    # backend refuses cross-process collectives outright ("Multiprocess
    # computations aren't implemented on the CPU backend") — the workers
    # rendezvous, form the topology, and die at the FIRST collective. On
    # a backend with cross-process collectives (TPU/GPU, or a CPU build
    # with Gloo-backed XLA collectives) the tests run and must pass, so
    # we probe the worker output for the exact refusal instead of
    # skipping unconditionally.
    gap = "Multiprocess computations aren't implemented on the CPU backend"
    if any(gap in out for out in outs):
        pytest.skip(
            "environment gap: jaxlib's CPU backend cannot run "
            f"cross-process collectives (XlaRuntimeError: {gap!r}); "
            "needs TPU/GPU or a CPU jaxlib with cross-process collective "
            "support"
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}:\n{out}"
        assert f"{marker} {i}" in out, f"worker {i} missing marker:\n{out}"


@pytest.mark.timeout(300)
def test_two_process_distributed_fm_hier():
    _run_worker_pair(_WORKER, [], "MP_OK", budget_s=240)


@pytest.mark.timeout(420)
def test_two_process_taskgraph_dag(tmp_path):
    """The full five-task DAG across 2 real processes sharing a filesystem:
    process-0-only writes with barriers, then an ASYMMETRIC-staleness rerun
    (one fresh state DB, one warm) that deadlocks without the runner's
    cross-process stale consensus."""
    _run_worker_pair(
        _TG_WORKER, [str(tmp_path)], "TG_OK", budget_s=360
    )
