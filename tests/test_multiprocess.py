"""REAL multi-process execution: spawned Python processes, no fictions.

Until ISSUE 13 both tests here skipped on this container — the jaxlib CPU
backend refuses cross-process device collectives, and everything
multi-process rode them. The ``parallel.distributed`` bootstrap's
host-side exchange removes that dependency, so this module is now the
tier-1 evidence of the cross-process claim, all of it against REAL
spawned subprocesses:

- host-exchange collectives (allgather / sum_tree / broadcast / barrier)
  plus the telemetry ``process_index`` identity, across 3 processes;
- the full taskgraph DAG across 2 processes sharing a filesystem, with
  process-0-only writes, exchange barriers, and the asymmetric-staleness
  consensus — running FOR REAL on the CPU backend;
- the multi-process spec-grid route differentially pinned against the
  single-process program (≤1e-6 f32 / ≤1e-13 f64 rtol, the mesh-route
  precedent), including the "only one worker compiles fresh" registry
  evidence;
- the serving fleet in ``replica_mode="process"``: a SIGKILLed replica
  process whose in-flight requests requeue and whose journal replays
  CLEAN (exactly-once across a process death), and warm-pool process
  spawns with zero-compile WarmReports plus a two-phase rollover over
  the wire.

The ONE remaining skip is the named environment gap it always was:
``jax.distributed`` device collectives on a CPU jaxlib without
cross-process collective support (``test_two_process_distributed_fm_hier``
probes the worker output for the exact refusal — on TPU/GPU it runs and
must pass).
"""

import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

_WORKER = Path(__file__).parent / "mp_worker.py"
_TG_WORKER = Path(__file__).parent / "mp_taskgraph_worker.py"
_EX_WORKER = Path(__file__).parent / "mp_exchange_worker.py"
_REPO = Path(__file__).parent.parent

pytestmark = pytest.mark.multiprocess


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_workers(worker: Path, extra_args, nprocs: int, budget_s: float):
    """Spawn ``nprocs`` copies of ``worker`` and gather their outputs
    within one shared wall budget."""
    port = _free_port()
    env = {**os.environ, "PYTHONPATH": str(_REPO)}
    # the parent's pytest env must not leak its 8-device flag into workers
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(nprocs), str(port),
             *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(nprocs)
    ]
    outs = []
    deadline = time.monotonic() + budget_s  # shared: total wait, not per-worker
    try:
        for p in procs:
            out, _ = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
            outs.append(out)
    except subprocess.TimeoutExpired:
        pytest.fail("distributed workers hung:\n" + "\n---\n".join(outs))
    finally:
        for p in procs:  # never leak workers holding the coordinator port
            if p.poll() is None:
                p.kill()
    return procs, outs


def _assert_ok(procs, outs, marker: str):
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}:\n{out}"
        assert f"{marker} {i}" in out, f"worker {i} missing marker:\n{out}"


# -- the host-exchange bootstrap (works on EVERY backend) --------------------


@pytest.mark.timeout(180)
def test_three_process_host_exchange_collectives():
    """allgather / sum_tree / broadcast / barrier across 3 spawned
    processes, plus the process_index telemetry identity the bootstrap
    stamps — the transport everything else in this module rides."""
    procs, outs = _spawn_workers(_EX_WORKER, [], nprocs=3, budget_s=120)
    _assert_ok(procs, outs, "EX_OK")


@pytest.mark.timeout(420)
def test_two_process_taskgraph_dag_host_exchange(tmp_path):
    """The full five-task DAG across 2 real processes sharing a
    filesystem — process-0-only writes with exchange barriers, then an
    ASYMMETRIC-staleness rerun (one fresh state DB, one warm) that
    deadlocks without the runner's cross-process stale consensus, then a
    one-sided failure that must stop both sides. Runs FOR REAL on the
    CPU backend: every collective is a host-exchange round."""
    procs, outs = _spawn_workers(
        _TG_WORKER, [str(tmp_path), "host"], nprocs=2, budget_s=360
    )
    _assert_ok(procs, outs, "TG_OK")


def test_barrier_tag_skew_raises():
    """Program-order divergence is a RAISE, not a hang: two ranks enter
    barriers with different tags and both get DistributedError naming the
    skew (the failure sync_global_devices would hide as a deadlock)."""
    import threading

    from fm_returnprediction_tpu.parallel.distributed import (
        DistConfig,
        DistributedError,
        HostExchange,
        free_port,
    )

    port = free_port()
    cfg = lambda r: DistConfig(  # noqa: E731
        coordinator=f"127.0.0.1:{port}", num_processes=2, process_id=r
    )
    errs = {}

    def rank(r, tag):
        ex = HostExchange(cfg(r), timeout_s=30.0)
        try:
            ex.barrier(tag)
        except DistributedError as exc:
            errs[r] = str(exc)
        finally:
            ex.close()

    t1 = threading.Thread(target=rank, args=(1, "phase_B"))
    t1.start()
    rank(0, "phase_A")
    t1.join(timeout=30)
    assert "tag skew" in errs[0] and "tag skew" in errs[1]


# -- the multi-process spec-grid route ---------------------------------------


def _mp_panel(rng, t=48, n=90, p=6, dtype=np.float64):
    x = rng.standard_normal((t, n, p)).astype(dtype)
    beta = rng.standard_normal(p) * 0.1
    y = (x @ beta + 0.2 * rng.standard_normal((t, n))).astype(dtype)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(dtype)
    size = rng.random(n)
    masks = {"All": mask, "Big": mask & (size > 0.4)[None, :]}
    return y, x, masks


def _mp_grid():
    from fm_returnprediction_tpu.specgrid import Spec, SpecGrid

    names = [f"x{i}" for i in range(6)]
    return SpecGrid(tuple(
        Spec(f"m{k} | {u}", tuple(names[:k]), u)
        for k in (3, 6) for u in ("All", "Big")
    ))


_GRID_FIELDS = ("coef", "tstat", "nw_se", "slopes", "intercept",
                "mean_r2", "mean_n", "r2", "month_valid")


def _assert_grid_close(ref, got, rtol, atol):
    for field in _GRID_FIELDS:
        a = np.asarray(getattr(ref, field), float)
        b = np.asarray(getattr(got, field), float)
        both_nan = np.isnan(a) & np.isnan(b)
        np.testing.assert_allclose(
            np.where(both_nan, 0.0, a), np.where(both_nan, 0.0, b),
            rtol=rtol, atol=atol, err_msg=field,
        )


@pytest.mark.specgrid
@pytest.mark.timeout(300)
@pytest.mark.parametrize("dtype,rtol,atol", [
    (np.float64, 1e-13, 1e-13),
    (np.float32, 1e-6, 1e-6),
])
def test_multiproc_specgrid_matches_single_process(dtype, rtol, atol):
    """The ISSUE-13 differential pin: 2 spawned contraction workers +
    host-exchange merge equals the in-process route to ≤1e-6 f32 /
    ≤1e-13 f64 (the mesh-route tolerance precedent), every output
    field. The additive-Gram property is what makes the shard merge
    exact; the rank-ordered sum_tree fold is what makes it
    deterministic."""
    from fm_returnprediction_tpu.specgrid import multiproc, run_spec_grid

    rng = np.random.default_rng(7)
    y, x, masks = _mp_panel(rng, dtype=dtype)
    grid = _mp_grid()
    try:
        ref = run_spec_grid(y, x, masks, grid)
        got = run_spec_grid(y, x, masks, grid, procs=2)
    finally:
        multiproc._close_cached_pool()
    _assert_grid_close(ref, got, rtol, atol)


@pytest.mark.specgrid
@pytest.mark.registry
@pytest.mark.timeout(300)
def test_multiproc_specgrid_only_one_worker_compiles(tmp_path):
    """With a registry armed, the pool's staggered warm-up means exactly
    ONE worker process pays the fresh contraction compile; the other
    deserializes — the per-worker cost-ledger provenance split is the
    evidence (`pool.last_reports`)."""
    from fm_returnprediction_tpu.specgrid import multiproc

    rng = np.random.default_rng(11)
    p = 4
    y, x, masks = _mp_panel(rng, t=36, n=60, p=p)
    uni = np.stack([masks["All"]]).astype(bool)
    uidx = np.zeros(1, np.int64)
    t = y.shape[0]
    window = np.ones((1, t), bool)
    col_sel = np.ones((1, p), bool)
    reg_dir = tmp_path / "registry"
    pool = multiproc.SpecGridWorkerPool(
        2, y, x, uni, child_env={"FMRP_REGISTRY_DIR": str(reg_dir)},
    )
    try:
        pool.contract(uidx, col_sel, window, report=True)
        reports = {r["rank"]: r for r in pool.last_reports}
        assert len(reports) == 2, reports
        fresh = sum(r["fresh"] for r in reports.values())
        fetched = sum(r["deserialized"] for r in reports.values())
        assert fresh == 1, f"exactly one fresh compile expected: {reports}"
        assert fetched == 1, f"the other worker must deserialize: {reports}"
        # transport accounting moved (the bench's multiproc_transport_*)
        assert pool.last_merge_bytes > 0 and pool.last_merge_s > 0
    finally:
        pool.close()


# -- the multi-process serving fleet -----------------------------------------


def _fleet_state(rng, t=48, n=120, p=4):
    from fm_returnprediction_tpu.serving import build_serving_state

    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = (rng.standard_normal(p) * 0.05).astype(np.float32)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(y, x, mask, window=24, min_periods=12)
    return state, y, x, mask


@pytest.mark.fleet
@pytest.mark.timeout(420)
def test_process_fleet_sigkill_replica_journal_replays_clean(tmp_path):
    """THE acceptance pin: replicas are real OS processes; one is
    SIGKILLed with requests in flight. The torn socket fails them with
    ReplicaDeadError, the router requeues onto the survivor, the
    supervisor's wire heartbeat detects the corpse and warm-replaces it,
    and the WAL journal — written by the router, so the kill cannot
    lose it — replays CLEAN: zero dropped, zero duplicated."""
    from fm_returnprediction_tpu.serving import ServingFleet, replay_journal

    rng = np.random.default_rng(0)
    state, _, x, _ = _fleet_state(rng)
    journal = tmp_path / "journal.jsonl"
    fleet = ServingFleet(state, 2, replica_mode="process",
                         journal=str(journal), max_batch=32,
                         max_latency_ms=2.0)
    try:
        assert fleet.replica_mode == "process"
        months = np.nonzero(state.have_coef())[0]
        xs = rng.standard_normal((40, state.n_predictors)).astype(np.float32)
        out = fleet.query_many(
            [int(months[i % len(months)]) for i in range(40)], xs
        )
        assert np.isfinite(out).sum() == 40

        rid = sorted(fleet.replica_states())[0]
        rep = fleet.replica(rid)
        child_pid = rep.service.pid
        futs = [fleet.submit(int(months[0]), xs[0]) for _ in range(10)]
        rep.service.proc.send_signal(signal.SIGKILL)  # a REAL process death
        rep.service.proc.wait(timeout=30)

        # the supervisor's stats probe is the heartbeat: the corpse cannot
        # answer, so the tick kills + (next tick) warm-replaces it
        deadline = time.monotonic() + 60
        while (fleet.replica_states().get(rid) not in (None, "dead")
               and time.monotonic() < deadline):
            fleet.supervisor.tick()
            time.sleep(0.05)
        fleet.supervisor.tick()  # DEAD → failover replacement

        res = np.asarray([f.result(timeout=60) for f in futs])
        assert np.isfinite(res).all(), "in-flight requests must survive"
        stats = fleet.stats()
        assert stats["requeues_total"] >= 1 or stats["failovers_total"] >= 1
        assert stats["healthy_replicas"] >= 2  # replacement spawned
        new_rids = set(fleet.replica_states()) - {rid}
        assert all(
            fleet.replica(r).service.pid != child_pid for r in new_rids
        ), "the replacement must be a NEW process"
    finally:
        fleet.close()
    replay = replay_journal(journal)
    assert replay.clean, (replay.dropped, replay.duplicated)
    assert replay.n_admitted == 50


@pytest.mark.fleet
@pytest.mark.registry
@pytest.mark.timeout(420)
def test_process_fleet_warm_spawn_and_rollover_over_the_wire(tmp_path):
    """Warm-pool process spawn: with a populated registry every replica
    CHILD starts zero-compile (WarmReport evidence rides back in the
    hello), and the two-phase rollover ships the candidate bundle over
    the shared filesystem — prepare warms in every child, commit flips,
    and the new month slot quotes."""
    from fm_returnprediction_tpu.registry.store import using_registry
    from fm_returnprediction_tpu.serving import (
        ERService,
        ServingFleet,
        ingest_month,
    )

    rng = np.random.default_rng(1)
    state, y, x, mask = _fleet_state(rng)
    new_state = ingest_month(
        state, y[-1], x[-1], mask[-1], np.datetime64("2035-01-31", "ns")
    )
    reg_dir = tmp_path / "registry"
    with using_registry(reg_dir):
        ERService(state, max_batch=32, auto_flush=False).close()
        ERService(new_state, max_batch=32, auto_flush=False).close()
    fleet = ServingFleet(state, 2, replica_mode="process",
                         registry_dir=reg_dir, max_batch=32)
    try:
        assert set(fleet.warm_reports) == set(fleet.replica_states())
        assert all(r.zero_compile for r in fleet.warm_reports.values()), (
            fleet.warm_reports
        )
        assert fleet.rollover(new_state) == 1
        q = fleet.query(int(new_state.n_months - 1),
                        np.zeros(state.n_predictors, np.float32))
        assert isinstance(q, float) or np.isscalar(q)
        # per-child telemetry identity: the replica's own export labels
        # itself (FMRP_PROC_INDEX threaded by the spawner)
        assert fleet.replica("r0").service.stats()["n_done"] >= 0
    finally:
        fleet.close()


# -- the named environment gap (device collectives) --------------------------


@pytest.mark.timeout(300)
def test_two_process_distributed_fm_hier():
    """``jax.distributed`` DEVICE collectives: 2 processes × 2 virtual
    CPU devices form the 4-device global topology and run a hierarchical
    Fama-MacBeth step whose psums actually cross the process boundary.

    Environment gap, not a code fault: this container's jaxlib CPU
    backend refuses cross-process collectives outright — the workers
    rendezvous, form the topology, and die at the FIRST collective. On a
    backend with cross-process collectives (TPU/GPU, or a CPU build with
    Gloo-backed XLA collectives) the test runs and must pass, so we
    probe the worker output for the exact refusal instead of skipping
    unconditionally. Every OTHER test in this module runs for real: the
    host-side exchange is the disclosed fallback for exactly this gap.
    """
    procs, outs = _spawn_workers(_WORKER, [], nprocs=2, budget_s=240)
    gap = "Multiprocess computations aren't implemented on the CPU backend"
    if any(gap in out for out in outs):
        pytest.skip(
            "environment gap: jaxlib's CPU backend cannot run "
            f"cross-process collectives (XlaRuntimeError: {gap!r}); "
            "needs TPU/GPU or a CPU jaxlib with cross-process collective "
            "support. The host-exchange tests above cover the fallback "
            "transport on this backend."
        )
    _assert_ok(procs, outs, "MP_OK")
