"""Pod-scale spec-grid: CellSpace tiling, sinks, sharding rules, coreset.

The ISSUE-8 contracts:

- tile-boundary equality — the streamed full-frame sink is BIT-IDENTICAL
  to the materialized (one-tile) route, whatever the tile width (per-spec
  independence of the fused program, pinned end to end through the frame);
- sharded-vs-single-device differential on the virtual CPU mesh, with the
  placements coming from ``parallel.partition``'s declarative rules;
- top-k sink determinism under tie values and across tile widths;
- coreset route disclosure fields (rate/m/suspect counts) + determinism;
- the lazy enumeration itself: index addressing, tiling coverage, and the
  one-compiled-program discipline of the tile engine.
"""

import dataclasses

import jax
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.specgrid import (
    CellSpace,
    FrameSink,
    SummarySink,
    TopKSink,
    block_bootstrap_months,
    program_trace_counts,
    resolve_sink,
    run_cellspace,
    run_spec_grid,
    scenario_space,
    specgrid_mesh,
)
from fm_returnprediction_tpu.specgrid.cellspace import resolve_tile_cells

pytestmark = [pytest.mark.specgrid, pytest.mark.specgrid_scale]


def _panel(rng, t=36, n=120, p=6, nan_frac=0.05):
    x = rng.standard_normal((t, n, p))
    beta = rng.standard_normal(p) * 0.1
    y = x @ beta + 0.2 * rng.standard_normal((t, n))
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan)
    x[rng.random((t, n, p)) < nan_frac] = np.nan
    size = rng.random(n)
    masks = {
        "All": mask,
        "Big": mask & (size > 0.4)[None, :],
    }
    return y, x, masks


def _space(p=6, **kw):
    names = tuple(f"x{i}" for i in range(p))
    defaults = dict(
        regressor_sets=(("m2", names[:2]), ("m4", names[:4]), ("m6", names)),
        universes=("All", "Big"),
        windows=(("full", None), ("late", (18, 36))),
    )
    defaults.update(kw)
    return CellSpace(**defaults)


# -- CellSpace addressing ---------------------------------------------------

def test_cellspace_indexing_roundtrip():
    space = _space(winsor_levels=(1.0, 5.0), weights=("reference", "textbook"),
                   bootstrap=3)
    assert len(space) == 2 * 2 * 3 * 2 * 2 * 3
    # decode every index once; the dimension odometer must roll
    # innermost-last and never repeat
    seen = set()
    for i in range(len(space)):
        c = space.cell(i)
        key = (c.winsor, c.weight, c.set_name, c.universe, c.window_name,
               c.draw)
        assert key not in seen
        seen.add(key)
        assert c.index == i
    assert len(seen) == len(space)
    # outermost dimension is winsor: the first half of the space is level 1
    assert all(space.cell(i).winsor == 1.0 for i in range(len(space) // 2))
    with pytest.raises(IndexError):
        space.cell(len(space))


def test_cellspace_tiles_cover_exactly_once():
    space = _space(bootstrap=2)
    for width in (1, 7, 64, 10_000):
        tiles = list(space.tiles(width))
        idx = [c.index for t in tiles for c in t.cells()]
        assert idx == list(range(len(space)))
        assert all(len(t) <= width for t in tiles)


def test_cellspace_spec_index_shared_across_draws_and_weights():
    space = _space(weights=("reference", "textbook"), bootstrap=4)
    by_spec = {}
    for i in range(len(space)):
        c = space.cell(i)
        sid = space.spec_index(i)
        key = (c.set_name, c.universe, c.window_name)
        by_spec.setdefault(sid, set()).add(key)
    # one spec id ↔ one (set, universe, window) triple
    assert all(len(v) == 1 for v in by_spec.values())
    assert len(by_spec) == space.n_specs


def test_resolve_tile_cells_env(monkeypatch):
    assert resolve_tile_cells(64) == 64
    monkeypatch.setenv("FMRP_SPECGRID_TILE", "33")
    assert resolve_tile_cells() == 33
    with pytest.raises(ValueError):
        resolve_tile_cells(0)


# -- tile-boundary equality -------------------------------------------------

def test_streamed_frame_bit_identical_to_materialized():
    """The acceptance bit-identity: any tile width through the FrameSink
    equals the one-tile materialized run EXACTLY — per-spec independence
    of the fused program carried through sinks and frame assembly."""
    rng = np.random.default_rng(11)
    y, x, masks = _panel(rng)
    space = _space(weights=("reference", "textbook"), bootstrap=2)
    ref, ref_stats = run_cellspace(y, x, masks, space,
                                   sink="frame", tile_cells=len(space),
                                   mask=masks["All"])
    assert ref_stats["tiles"] == 1
    for width in (5, 16, 50):
        got, stats = run_cellspace(y, x, masks, space, sink="frame",
                                   tile_cells=width, mask=masks["All"])
        # tile width rounds up to a draw-run multiple so a spec's draws
        # never straddle tiles (no re-contraction of a straddled spec)
        effective = min(len(space),
                        -(-width // space.bootstrap) * space.bootstrap)
        assert stats["tile_cells"] == effective
        assert stats["tiles"] == -(-len(space) // effective)
        pd.testing.assert_frame_equal(got, ref)


def test_tile_engine_single_compiled_program():
    """A multi-tile sweep costs ONE fused-program trace (fixed spec_pad,
    pinned union, full static weight tuple) — the compile discipline the
    bench's recompile_watch enforces at scale."""
    rng = np.random.default_rng(13)
    y, x, masks = _panel(rng, nan_frac=0.0)
    space = _space(bootstrap=2)
    before = program_trace_counts()
    _, stats = run_cellspace(y, x, masks, space, sink="summary",
                             tile_cells=7, mask=masks["All"])
    after = program_trace_counts()
    assert stats["tiles"] >= 3
    # ONE trace across the route's program names — a window-sweeping
    # space resolves factorize="auto" to the factorized program, a
    # single-window one to the legacy program; either way the whole
    # sweep traces exactly once
    traced = sum(
        after.get(k, 0) - before.get(k, 0)
        for k in ("specgrid_program", "specgrid_program_fact")
    )
    assert traced == 1


def test_run_scenarios_rides_the_tile_engine():
    """``run_scenarios`` output through the lazy engine: same tidy schema,
    winsor-major row order, and the cell address column."""
    rng = np.random.default_rng(17)

    class _MiniPanel:
        def __init__(self, y, x, mask, names):
            self._y, self._x, self.mask = y, x, mask
            self._names = names
            self.months = np.arange(y.shape[0])

        def var(self, name):
            return self._y

        def select(self, cols):
            idx = [self._names.index(c) for c in cols]
            return self._x[:, :, idx]

    from fm_returnprediction_tpu.models.lewellen import ModelSpec
    from fm_returnprediction_tpu.specgrid import run_scenarios

    y, x, masks = _panel(rng, p=3)
    panel = _MiniPanel(y, x, masks["All"], ["c0", "c1", "c2"])
    variables = {"V0": "c0", "V1": "c1", "V2": "c2"}
    models = [ModelSpec("Model A", ["V0", "V1"]),
              ModelSpec("Model B", ["V0", "V1", "V2"])]
    frame, stats = run_scenarios(
        panel, masks, variables, models=models, universes=["All", "Big"],
        subperiods=2, tile_cells=4, return_stats=True,
    )
    assert stats["tiles"] == -(-stats["cells"] // 4)
    # 2 models × 2 universes × 3 windows, rows = Σ predictors per model
    assert len(frame) == 2 * 3 * (2 + 3)
    assert list(frame["cell"]) == sorted(frame["cell"])
    # cells=N scales the draw dimension until the space covers N
    big, big_stats = run_scenarios(
        panel, masks, variables, models=models, universes=["All", "Big"],
        subperiods=2, cells=100, sink="summary", return_stats=True,
    )
    assert big_stats["cells"] >= 100
    assert {"column", "count", "mean"} <= set(big.columns)


# -- sinks ------------------------------------------------------------------

def test_topk_sink_deterministic_under_ties():
    """Exact tie values resolve by the cell's global address, so any tile
    width — and any consume order of equal values — yields the same
    board."""
    cols = ["cell", "model", "predictor", "tstat"]
    rows = [
        [0, "a", "p0", 2.0], [1, "a", "p0", -2.0], [2, "a", "p0", 2.0],
        [3, "a", "p0", 1.0], [4, "a", "p0", -3.0], [5, "a", "p0", 2.0],
    ]
    frame = pd.DataFrame(rows, columns=cols)
    boards = []
    for split in (1, 2, 3, 6):
        sink = TopKSink(k=4)
        for start in range(0, len(frame), split):
            sink.consume(frame.iloc[start:start + split].reset_index(drop=True))
        boards.append(sink.finish())
    for b in boards[1:]:
        pd.testing.assert_frame_equal(b, boards[0])
    board = boards[0]
    # |t|: 3.0 first, then the 2.0 ties in cell order (0, 1, 2)
    assert list(board["cell"]) == [4, 0, 1, 2]
    # NaN metrics never enter the board
    sink = TopKSink(k=10)
    sink.consume(pd.DataFrame([[9, "a", "p0", np.nan]], columns=cols))
    assert len(sink.finish()) == 0


def test_summary_sink_matches_full_frame_moments():
    rng = np.random.default_rng(19)
    y, x, masks = _panel(rng)
    space = _space()
    full, _ = run_cellspace(y, x, masks, space, sink="frame",
                            mask=masks["All"])
    summary, _ = run_cellspace(y, x, masks, space, sink="summary",
                               tile_cells=9, mask=masks["All"])
    row = summary.set_index("column").loc["tstat"]
    ref = full["tstat"].to_numpy()
    fin = np.isfinite(ref)
    assert row["count"] == fin.sum()
    np.testing.assert_allclose(row["mean"], ref[fin].mean(), rtol=1e-12)
    np.testing.assert_allclose(row["std"], ref[fin].std(ddof=1), rtol=1e-10)
    np.testing.assert_allclose(row["min"], ref[fin].min(), rtol=1e-12)


def test_parquet_sink_spills_parts(tmp_path):
    rng = np.random.default_rng(23)
    y, x, masks = _panel(rng)
    space = _space()
    manifest, stats = run_cellspace(
        y, x, masks, space, sink="parquet", tile_cells=10,
        mask=masks["All"], output_dir=tmp_path,
    )
    assert len(manifest) == stats["tiles"]
    assert manifest["rows"].sum() == stats["rows"]
    parts = [pd.read_parquet(p) if str(p).endswith("parquet")
             else pd.read_csv(p) for p in manifest["path"]]
    whole = pd.concat(parts, ignore_index=True)
    full, _ = run_cellspace(y, x, masks, space, sink="frame",
                            mask=masks["All"])
    assert len(whole) == len(full)
    np.testing.assert_allclose(whole["coef"], full["coef"], rtol=0, atol=0)


def test_resolve_sink_env(monkeypatch):
    from fm_returnprediction_tpu.specgrid.sinks import resolve_sink_name

    assert isinstance(resolve_sink(None), FrameSink)
    assert resolve_sink_name(None) == "frame"
    monkeypatch.setenv("FMRP_SPECGRID_SINK", "topk")
    assert isinstance(resolve_sink(None), TopKSink)
    # the name resolver must see the env-selected sink too — guard gates
    # on it to skip the tidy-frame contract for non-frame schemas
    assert resolve_sink_name(None) == "topk"
    assert resolve_sink_name(SummarySink()) == "summary"
    assert isinstance(resolve_sink("summary"), SummarySink)
    s = SummarySink()
    assert resolve_sink(s) is s
    with pytest.raises(ValueError):
        resolve_sink("parquet")  # needs an output dir
    with pytest.raises(ValueError):
        resolve_sink("nope")


# -- bootstrap draws --------------------------------------------------------

def test_bootstrap_draws_deterministic_and_distinct():
    rng = np.random.default_rng(29)
    y, x, masks = _panel(rng)
    space = _space(bootstrap=4)
    f1, _ = run_cellspace(y, x, masks, space, sink="frame",
                          mask=masks["All"], seed=7)
    f2, _ = run_cellspace(y, x, masks, space, sink="frame",
                          mask=masks["All"], seed=7)
    pd.testing.assert_frame_equal(f1, f2)
    # draw 0 is the point estimate; other draws move the coef
    one = f1[(f1.model == "m4") & (f1.universe == "All")
             & (f1.window == "full") & (f1.predictor == "x0")]
    assert len(one) == 4
    assert one[one.draw == 0]["coef"].notna().all()
    assert one["coef"].nunique() > 1
    # the resample itself is deterministic and covers T indices
    idx = block_bootstrap_months(36, draw=1, seed=7)
    np.testing.assert_array_equal(idx, block_bootstrap_months(36, 1, seed=7))
    assert idx.shape == (36,) and idx.min() >= 0 and idx.max() < 36
    with pytest.raises(ValueError):
        block_bootstrap_months(36, draw=0)


def test_draw_zero_matches_no_bootstrap_run():
    """Adding the draw dimension must not move the point estimates."""
    rng = np.random.default_rng(31)
    y, x, masks = _panel(rng)
    space1 = _space()
    space4 = _space(bootstrap=4)
    f1, _ = run_cellspace(y, x, masks, space1, sink="frame",
                          mask=masks["All"])
    f4, _ = run_cellspace(y, x, masks, space4, sink="frame",
                          mask=masks["All"])
    point = f4[f4.draw == 0].drop(columns=["cell", "draw"]).reset_index(
        drop=True)
    base = f1.drop(columns=["cell"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(point, base)


# -- sharded solve ----------------------------------------------------------

def test_partition_rules_match_and_unmatched_raises():
    from jax.sharding import PartitionSpec as P

    from fm_returnprediction_tpu.parallel.partition import (
        match_partition_rules,
        specgrid_panel_rules,
        specgrid_stats_rules,
    )

    tree = {
        "y": np.zeros((4, 8)), "x": np.zeros((4, 8, 3)),
        "universes": np.zeros((2, 4, 8)), "uidx": np.zeros(5),
        "col_sel": np.zeros((5, 3)), "window": np.zeros((5, 4)),
        "scalar": np.float64(1.0),
    }
    specs = match_partition_rules(specgrid_panel_rules("cells"), tree)
    assert specs["y"] == P(None, "cells")
    assert specs["x"] == P(None, "cells", None)
    assert specs["universes"] == P(None, None, "cells")
    assert specs["uidx"] == P()
    assert specs["scalar"] == P()  # scalars never partition
    stats = match_partition_rules(
        specgrid_stats_rules("cells"),
        {"gram": np.zeros((5, 4, 4, 4)), "center": np.zeros((4, 3))},
    )
    assert stats["gram"] == P("cells")
    assert stats["center"] == P()
    with pytest.raises(ValueError, match="partition rule not found"):
        match_partition_rules(specgrid_panel_rules(), {"mystery": np.zeros(9)})


def test_sharded_solve_matches_single_device():
    """The acceptance differential: the mesh route (declared partition
    rules, psum'd firm contraction, spec-sharded solve) matches the
    single-device route to the PR-3 tolerances on the virtual CPU mesh —
    including a padded spec count (S=6 over 8 devices exercises the ghost
    specs)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the conftest 8-virtual-device CPU backend")
    from fm_returnprediction_tpu.specgrid import Spec, SpecGrid

    rng = np.random.default_rng(37)
    y, x, masks = _panel(rng, t=30, n=96, p=5)
    names = [f"x{i}" for i in range(5)]
    grid = SpecGrid(tuple(
        Spec(f"m{k} | {u}", tuple(names[:k]), u)
        for k in (2, 5) for u in masks
    ) + (Spec("late | All", tuple(names[:3]), "All", window=(10, 30)),))
    mesh = specgrid_mesh(len(jax.devices()))
    single = run_spec_grid(y, x, masks, grid)
    shard = run_spec_grid(y, x, masks, grid, mesh=mesh)
    for field in ("coef", "tstat", "nw_se", "mean_r2", "mean_n",
                  "slopes", "intercept", "r2", "n_obs"):
        a = np.asarray(getattr(single, field), float)
        b = np.asarray(getattr(shard, field), float)
        both_nan = np.isnan(a) & np.isnan(b)
        np.testing.assert_allclose(
            np.where(both_nan, 0.0, a), np.where(both_nan, 0.0, b),
            rtol=1e-6, atol=1e-6, err_msg=field,
        )
    np.testing.assert_array_equal(single.month_valid, shard.month_valid)
    np.testing.assert_array_equal(single.n_months, shard.n_months)


def test_sharded_engine_sweep_matches_single_device_sweep():
    """End to end through the tile engine: a mesh-routed sweep equals the
    single-device sweep to solver tolerance, frame for frame."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the conftest 8-virtual-device CPU backend")
    rng = np.random.default_rng(41)
    y, x, masks = _panel(rng, nan_frac=0.0)
    space = _space()
    mesh = specgrid_mesh(len(jax.devices()))
    f_single, _ = run_cellspace(y, x, masks, space, sink="frame",
                                mask=masks["All"])
    f_mesh, _ = run_cellspace(y, x, masks, space, sink="frame",
                              mask=masks["All"], mesh=mesh)
    assert list(f_single.columns) == list(f_mesh.columns)
    for col in ("coef", "tstat", "nw_se", "mean_r2"):
        a = f_single[col].to_numpy()
        b = f_mesh[col].to_numpy()
        both_nan = np.isnan(a) & np.isnan(b)
        np.testing.assert_allclose(
            np.where(both_nan, 0, a), np.where(both_nan, 0, b),
            rtol=1e-6, atol=1e-6, err_msg=col,
        )


def test_resolve_specgrid_mesh_env(monkeypatch):
    from fm_returnprediction_tpu.specgrid import resolve_specgrid_mesh

    monkeypatch.delenv("FMRP_SPECGRID_MESH", raising=False)
    assert resolve_specgrid_mesh(None) is None
    monkeypatch.setenv("FMRP_SPECGRID_MESH", "0")
    assert resolve_specgrid_mesh(None) is None
    if len(jax.devices()) >= 2:
        monkeypatch.setenv("FMRP_SPECGRID_MESH", "2")
        mesh = resolve_specgrid_mesh(None)
        assert mesh is not None and mesh.devices.size == 2
        monkeypatch.setenv("FMRP_SPECGRID_MESH", "auto")
        assert resolve_specgrid_mesh(None).devices.size == len(jax.devices())
    explicit = specgrid_mesh(1)
    assert resolve_specgrid_mesh(explicit) is explicit


# -- coreset route ----------------------------------------------------------

def test_coreset_route_disclosure_and_determinism():
    rng = np.random.default_rng(43)
    y, x, masks = _panel(rng, n=300, nan_frac=0.0)
    space = _space(p=6)
    f, stats = run_cellspace(y, x, masks, space, sink="frame",
                             mask=masks["All"], route="coreset",
                             coreset_m=128, seed=5)
    assert stats["route"] == "coreset"
    assert stats["coreset_m"] == 128
    assert {"route", "coreset_m", "coreset_rate", "suspect_months"} <= set(
        f.columns
    )
    assert (f["route"] == "coreset").all()
    assert (f["coreset_m"] == 128).all()
    assert ((f["coreset_rate"] > 0) & (f["coreset_rate"] <= 1)).all()
    # the referee is structurally off on the approximation tier
    assert not f["refereed"].any()
    f2, _ = run_cellspace(y, x, masks, space, sink="frame",
                          mask=masks["All"], route="coreset",
                          coreset_m=128, seed=5)
    pd.testing.assert_frame_equal(f, f2)


def test_coreset_estimates_approach_exact_with_budget():
    """The unbiasedness story: a generous draw budget lands near the exact
    route; months with fewer valid rows than m stay exactly equal."""
    rng = np.random.default_rng(47)
    y, x, masks = _panel(rng, n=250, nan_frac=0.0)
    space = _space(p=6, regressor_sets=(("m4", tuple(f"x{i}" for i in range(4))),),
                   universes=("All",), windows=(("full", None),))
    x4 = x[:, :, :4]
    exact, _ = run_cellspace(y, x4, masks, space, sink="frame",
                             mask=masks["All"])
    approx, _ = run_cellspace(y, x4, masks, space, sink="frame",
                              mask=masks["All"], route="coreset",
                              coreset_m=200, seed=3)
    np.testing.assert_allclose(approx["coef"], exact["coef"],
                               rtol=0.5, atol=0.02)
    # m >= every month's width → the plan is exact and so are the numbers
    from fm_returnprediction_tpu.specgrid import coreset_plan

    plan = coreset_plan(y, x4, masks["All"], m_per_month=10_000, seed=0)
    assert plan.exact_months == y.shape[0]
    ex2, _ = run_cellspace(y, x4, masks, space, sink="frame",
                           mask=masks["All"], route="coreset",
                           coreset_m=10_000)
    for col in ("coef", "tstat", "mean_r2"):
        np.testing.assert_allclose(ex2[col], exact[col], rtol=1e-10,
                                   atol=1e-12, err_msg=col)


def test_coreset_rejected_by_reporting_routes():
    from fm_returnprediction_tpu.specgrid import resolve_route

    assert resolve_route("coreset") == "coreset"
    with pytest.raises(ValueError, match="not available here"):
        resolve_route("coreset", allowed=("gram", "stacked"))


def test_taskgraph_specgrid_knob_staleness(tmp_path, monkeypatch):
    """The specgrid task's uptodate gate: a knob change in EITHER
    direction (incl. env-selected sinks) invalidates the cached artifact;
    matching knobs — and legacy sidecar-less default builds — stay
    current."""
    import json

    from fm_returnprediction_tpu.taskgraph.tasks import (
        SPECGRID_KNOBS_FILE,
        _specgrid_effective_knobs,
        _specgrid_knobs_unchanged,
    )

    monkeypatch.delenv("FMRP_SPECGRID_SINK", raising=False)
    # no sidecar: default invocation current, knobbed invocation stale
    assert _specgrid_knobs_unchanged(tmp_path, None, None)
    assert not _specgrid_knobs_unchanged(tmp_path, 1000, None)
    # env-selected sink counts as a knob even with no CLI args
    monkeypatch.setenv("FMRP_SPECGRID_SINK", "topk")
    assert not _specgrid_knobs_unchanged(tmp_path, None, None)
    # sidecar round-trip: built-under knobs must match exactly
    with open(tmp_path / SPECGRID_KNOBS_FILE, "w") as f:
        json.dump(_specgrid_effective_knobs(5000, "topk"), f)
    assert _specgrid_knobs_unchanged(tmp_path, 5000, "topk")
    assert not _specgrid_knobs_unchanged(tmp_path, 5000, "summary")
    monkeypatch.delenv("FMRP_SPECGRID_SINK")
    # back-to-default after a knobbed build is ALSO stale
    assert not _specgrid_knobs_unchanged(tmp_path, None, None)


# -- tier-2: the scale sweep ------------------------------------------------

@pytest.mark.slow
def test_scale_sweep_streams_bounded():
    """Tier-2: a ~2·10⁴-cell sweep through the top-k sink — completes,
    covers every cell exactly once, keeps the full frame unmaterialized,
    and costs one fused-program trace."""
    rng = np.random.default_rng(53)
    y, x, masks = _panel(rng, t=48, n=200, p=6)
    base = _space(weights=("reference",))
    space = dataclasses.replace(base, bootstrap=-(-20_000 // base.n_specs))
    assert len(space) >= 20_000
    sink = TopKSink(k=32)
    before = program_trace_counts()
    board, stats = run_cellspace(y, x, masks, space, sink=sink,
                                 tile_cells=512, mask=masks["All"])
    after = program_trace_counts()
    assert stats["cells"] == len(space)
    assert sink.cells_seen == len(space)
    assert len(board) == 32
    assert board["tstat"].abs().is_monotonic_decreasing
    traced = sum(
        after.get(k, 0) - before.get(k, 0)
        for k in ("specgrid_program", "specgrid_program_fact")
    )
    assert traced == 1
