"""Multi-host layer on the virtual 8-device CPU mesh.

Exercises the months×firms hierarchical mesh (``parallel.multihost``): the
2-D ``fama_macbeth_hier`` must agree with the single-chip solver and the
1-D firm-sharded path — including month padding when T does not divide the
host axis, and the near-singular boundary months the TSQR path exists for.
On virtual CPU devices the collectives compile to the same HLO a pod would
run; only the physical transport differs (module docstring).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
from fm_returnprediction_tpu.parallel import (
    as_flat_mesh,
    block_bootstrap_se,
    fama_macbeth_hier,
    initialize_multihost,
    make_mesh,
    make_mesh_2d,
)
from fm_returnprediction_tpu.panel.dense import long_to_dense

from oracle import make_synthetic_long_panel


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(47)
    df, pred_cols = make_synthetic_long_panel(rng)
    dense = long_to_dense(df, "mthcaldt", "permno", ["retx"] + pred_cols)
    y = jnp.asarray(dense.var("retx"))
    x = jnp.asarray(dense.select(pred_cols))
    mask = jnp.asarray(dense.mask)
    return y, x, mask


def test_initialize_multihost_single_process_noop(monkeypatch):
    monkeypatch.delenv("FMRP_MULTIHOST", raising=False)
    assert initialize_multihost() == (0, 1)


def test_make_mesh_2d_shapes_and_validation():
    mesh = make_mesh_2d(month_shards=2)
    assert mesh.shape == {"months": 2, "firms": 4}
    assert mesh.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        make_mesh_2d(month_shards=3)  # 8 devices don't factor
    with pytest.raises(ValueError):
        make_mesh_2d(month_shards=0)
    flat = as_flat_mesh(mesh)
    assert flat.shape == {"boot": 8}
    assert set(d.id for d in flat.devices.flat) == set(
        d.id for d in mesh.devices.flat
    )


@pytest.mark.parametrize("month_shards", [2, 4])
def test_hier_fm_matches_single_chip(panel, month_shards):
    y, x, mask = panel
    mesh = make_mesh_2d(month_shards=month_shards)
    cs_h, fm_h = fama_macbeth_hier(y, x, mask, mesh=mesh)
    cs_1, fm_1 = fama_macbeth(y, x, mask)

    assert cs_h.slopes.shape == cs_1.slopes.shape
    np.testing.assert_array_equal(
        np.asarray(cs_h.month_valid), np.asarray(cs_1.month_valid)
    )
    valid = np.asarray(cs_1.month_valid)
    np.testing.assert_allclose(
        np.asarray(cs_h.slopes)[valid], np.asarray(cs_1.slopes)[valid],
        rtol=1e-6, atol=1e-9,
    )
    np.testing.assert_allclose(
        np.asarray(cs_h.r2)[valid], np.asarray(cs_1.r2)[valid],
        rtol=1e-6, atol=1e-9,
    )
    np.testing.assert_allclose(
        np.asarray(fm_h.coef), np.asarray(fm_1.coef), rtol=1e-6, atol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(fm_h.tstat), np.asarray(fm_1.tstat), rtol=1e-5, atol=1e-8
    )


def test_hier_fm_gram_fast_path_matches_single_chip(panel):
    """n_refine=0 selects the Gram/psum fast path inside the 2-D mesh; on
    well-conditioned panels it must agree with the single-chip solver."""
    y, x, mask = panel
    mesh = make_mesh_2d(month_shards=2)
    _, fm_h = fama_macbeth_hier(y, x, mask, mesh=mesh, n_refine=0)
    _, fm_1 = fama_macbeth(y, x, mask)
    np.testing.assert_allclose(
        np.asarray(fm_h.coef), np.asarray(fm_1.coef), rtol=1e-6, atol=1e-9
    )


def test_hier_fm_default_mesh(panel):
    """mesh=None self-builds the (process_count, local) hierarchy — a (1, 8)
    mesh on a single process — and still matches the single-chip result."""
    y, x, mask = panel
    cs_h, fm_h = fama_macbeth_hier(y, x, mask)
    _, fm_1 = fama_macbeth(y, x, mask)
    np.testing.assert_allclose(
        np.asarray(fm_h.coef), np.asarray(fm_1.coef), rtol=1e-6, atol=1e-9
    )


def test_hier_fm_month_padding(panel):
    """A month count that does not divide the 4-row month axis pads up;
    padded months must be invisible (exactly like reference-skipped months)
    and the returned per-month result must be trimmed back to T."""
    y, x, mask = panel
    t = y.shape[0] - 1 if (y.shape[0] - 1) % 4 else y.shape[0] - 3
    assert t % 4 != 0
    y, x, mask = y[:t], x[:t], mask[:t]
    mesh = make_mesh_2d(month_shards=4)
    cs_h, fm_h = fama_macbeth_hier(y, x, mask, mesh=mesh)
    cs_1, fm_1 = fama_macbeth(y, x, mask)
    assert cs_h.slopes.shape[0] == t
    np.testing.assert_array_equal(
        np.asarray(cs_h.month_valid), np.asarray(cs_1.month_valid)
    )
    np.testing.assert_allclose(
        np.asarray(fm_h.coef), np.asarray(fm_1.coef), rtol=1e-6, atol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(fm_h.nw_se), np.asarray(fm_1.nw_se), rtol=1e-6, atol=1e-9
    )
    assert int(fm_h.n_months) == int(fm_1.n_months)


def test_hier_fm_near_singular_months():
    """Boundary months (n = P+1, cond ~ 1e6) agree with single-chip lstsq —
    the regime the TSQR path exists for, now through the 2-D mesh."""
    rng = np.random.default_rng(3)
    t, n, p = 12, 64, 5
    x = rng.standard_normal((t, n, p))
    y = rng.standard_normal((t, n))
    mask = np.ones((t, n), bool)
    # make half the months boundary months: only P+1 valid rows, nearly
    # collinear design
    for ti in range(0, t, 2):
        mask[ti, p + 1 :] = False
        base = rng.standard_normal(p)
        for r in range(p + 1):
            x[ti, r] = base + 1e-6 * rng.standard_normal(p)
    y = jnp.asarray(np.where(mask, y, np.nan))
    x = jnp.asarray(x)
    mask = jnp.asarray(mask)

    mesh = make_mesh_2d(month_shards=2)
    cs_h, _ = fama_macbeth_hier(y, x, mask, mesh=mesh)
    cs_1, _ = fama_macbeth(y, x, mask)
    valid = np.asarray(cs_1.month_valid)
    assert valid.all()
    drift = np.abs(np.asarray(cs_h.slopes) - np.asarray(cs_1.slopes)).max()
    assert drift < 1e-6, f"hier FM drifts {drift:.3e} from lstsq"


def test_hier_fm_collective_contract(panel):
    """The hierarchical program's communication contract, asserted on the
    compiled HLO: every collective is a psum (all-reduce) — the firm-axis
    TSQR/stats reductions and the month-axis slope gather. No all-gather,
    no all-to-all, no collective-permute, no reduce-scatter: the month axis
    exists so DCN carries ONE small reduction, and the psum-placed gather
    (not lax.all_gather) is what the replication checker admits."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fm_returnprediction_tpu.parallel.multihost import _jitted_fm_hier

    y, x, mask = panel
    mesh = make_mesh_2d(month_shards=2)
    t = y.shape[0] - y.shape[0] % 2
    n = x.shape[1] - x.shape[1] % 4
    s2 = NamedSharding(mesh, P("months", "firms"))
    s3 = NamedSharding(mesh, P("months", "firms", None))
    args = (
        jax.device_put(y[:t, :n], s2),
        jax.device_put(x[:t, :n], s3),
        jax.device_put(mask[:t, :n], s2),
    )
    run = _jitted_fm_hier(mesh, "months", "firms", 4, 10, "reference", 1)
    hlo = run.lower(*args).compile().as_text()
    assert "all-reduce" in hlo, "expected psum collectives in the hier program"
    for op in ("all-gather", "collective-permute", "all-to-all",
               "reduce-scatter"):
        assert op not in hlo, f"unexpected collective {op} in hier FM program"


def test_table2_on_hier_mesh_matches_single_device():
    """build_table_2 accepts the 2-D months×firms mesh and reproduces the
    single-device table cell for cell (formatted output equality)."""
    import pandas as pd

    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.pipeline import build_panel
    from fm_returnprediction_tpu.reporting.table2 import build_table_2

    data = generate_synthetic_wrds(SyntheticConfig(n_firms=60, n_months=60))
    panel, factors = build_panel(data)
    masks = compute_subset_masks(panel)
    t2_one = build_table_2(panel, masks, factors)
    t2_hier = build_table_2(
        panel, masks, factors, mesh=make_mesh_2d(month_shards=2)
    )
    pd.testing.assert_frame_equal(t2_one, t2_hier)


def test_build_panel_on_hier_mesh_matches_single_device():
    """The whole panel build accepts the 2-D mesh: the daily stage flattens
    it to one firm axis (zero collectives) and the result matches the
    single-device build exactly."""
    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.pipeline import build_panel

    data = generate_synthetic_wrds(SyntheticConfig(n_firms=50, n_months=54))
    panel_one, _ = build_panel(data)
    panel_hier, _ = build_panel(data, mesh=make_mesh_2d(month_shards=2))
    np.testing.assert_allclose(
        np.asarray(panel_one.values), np.asarray(panel_hier.values),
        rtol=1e-12, atol=1e-12, equal_nan=True,
    )


def test_bootstrap_on_flattened_hier_mesh(panel):
    """The replicate-sharded bootstrap over as_flat_mesh(2-D) must equal the
    plain 1-D mesh result (same key → same replicate draws)."""
    y, x, mask = panel
    cs, _ = fama_macbeth(y, x, mask)
    slope_valid = cs.month_valid[:, None] & jnp.isfinite(cs.slopes)
    key = jax.random.key(11)
    flat = as_flat_mesh(make_mesh_2d(month_shards=2))
    res_flat = block_bootstrap_se(
        cs.slopes, slope_valid, key, n_replicates=64, mesh=flat
    )
    res_1d = block_bootstrap_se(
        cs.slopes, slope_valid, key, n_replicates=64,
        mesh=make_mesh(axis_name="boot"),
    )
    np.testing.assert_allclose(
        np.asarray(res_flat.se), np.asarray(res_1d.se), rtol=1e-7, atol=1e-12
    )
