"""The doit-compat shim (``dodo.py``): task discovery surface and dict
contract, testable without doit installed (the shim only *exposes* the
graph; doit itself is optional)."""

import importlib.util
import subprocess
import sys
from pathlib import Path

_REPO = Path(__file__).parent.parent


def _load_dodo():
    spec = importlib.util.spec_from_file_location("dodo", _REPO / "dodo.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_task_creators_cover_the_graph():
    dodo = _load_dodo()
    creators = {n: f for n, f in vars(dodo).items() if n.startswith("task_")}
    # the five core build stages must be exposed under their native names
    for name in ("config", "pull_data", "build_panel", "reports", "latex"):
        assert f"task_{name}" in creators, f"task_{name} missing"
    for name, creator in creators.items():
        d = creator()
        assert callable(d["actions"][0]) or isinstance(d["actions"][0], str)
        assert isinstance(d["file_dep"], list)
        assert isinstance(d["targets"], list)
        assert all(isinstance(p, str) for p in d["file_dep"] + d["targets"])
        assert isinstance(d["doc"], str) and d["doc"], name


def test_direct_run_points_at_native_runner():
    if importlib.util.find_spec("doit") is not None:
        import pytest

        pytest.skip("doit installed: `python dodo.py` delegates to a real "
                    "doit build instead of printing the pointer")
    out = subprocess.run(
        [sys.executable, str(_REPO / "dodo.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0
    assert "fm_returnprediction_tpu.taskgraph" in out.stdout
