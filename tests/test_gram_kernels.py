"""The MXU-tiled pallas Gram kernel vs the retained XLA oracle, and the
bf16 mixed-precision contraction route.

The pallas kernel runs in interpreter mode on the CPU test backend (the
TPU compile path is exercised by bench.py on real hardware); the XLA chunk
loop in ``specgrid.grams`` is the differential oracle and stays the
default route off-TPU. Pins:

- f32 parity at 1e-6 RELATIVE across thin months, all-NaN columns and
  mask edges (absolute diffs scale with the Gram entries);
- f64 parity at the few-ulp level (1e-13 relative — the two routes block
  their reductions differently, so exact bitwise equality is not promised;
  counts ARE exactly equal);
- bf16: f32-storage outputs, EXACT integer counts, agreement between the
  bf16-XLA and bf16-pallas contractions, and the conditioning referee's
  per-month promotion (suspect months) disclosed and re-solved by the
  full-precision QR route through ``run_spec_grid``;
- route/precision knob resolution and the byte-identical default jaxpr.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_returnprediction_tpu.specgrid.grams import (
    contract_spec_grams,
    resolve_gram_precision,
    resolve_gram_route,
)

pytestmark = pytest.mark.kernels


def _panel(seed=0, t=13, n=301, p=5, s=4, u=2, dtype=np.float32):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(dtype)
    x[rng.random(x.shape) < 0.1] = np.nan
    x[:, 7, 2] = np.nan                       # an all-NaN firm column
    y = rng.standard_normal((t, n)).astype(dtype)
    y[rng.random(y.shape) < 0.15] = np.nan
    y[:, 11] = np.nan                         # a y-less firm
    universes = rng.random((u, t, n)) > 0.3
    universes[0, 3] = False                   # a month with an empty universe
    uidx = np.arange(s) % u
    col_sel = rng.random((s, p)) > 0.4
    col_sel[0] = [True] + [False] * (p - 1)   # univariate spec
    col_sel[-1] = True                        # full union spec
    window = np.ones((s, t), bool)
    window[s - 1, : min(6, t - 1)] = False    # subperiod window edge
    window[1, 0] = False
    return tuple(
        jnp.asarray(a) for a in (y, x, universes, uidx, col_sel, window)
    )


def _stats_close(a, b, rtol, counts_exact=True):
    for name in ("gram", "moment", "n", "ysum", "yy", "center"):
        av, bv = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        scale = max(np.max(np.abs(av)), 1.0)
        if counts_exact and name == "n":
            np.testing.assert_array_equal(av, bv, err_msg=name)
        else:
            np.testing.assert_allclose(bv, av, rtol=0, atol=rtol * scale,
                                       err_msg=name)


def test_pallas_matches_xla_f32():
    args = _panel()
    ref = contract_spec_grams(*args)
    pal = contract_spec_grams(*args, route="pallas", block_n=128,
                              interpret=True)
    _stats_close(ref, pal, rtol=1e-6)


def test_pallas_matches_xla_thin_month_and_ragged_blocks():
    # n NOT a multiple of any lane block → the NaN/zero pad path; a thin
    # month (nearly-empty universe) exercises the n < Q regime the solve's
    # structural referee gates on
    args = list(_panel(seed=3, t=7, n=137, p=4, s=3))
    uni = np.asarray(args[2]).copy()
    uni[:, 5, 4:] = False                    # month 5: at most 4 valid rows
    args[2] = jnp.asarray(uni)
    ref = contract_spec_grams(*args)
    pal = contract_spec_grams(*args, route="pallas", block_n=128,
                              interpret=True)
    _stats_close(ref, pal, rtol=1e-6)


def test_pallas_matches_xla_row_weights():
    args = _panel(seed=5)
    rng = np.random.default_rng(11)
    rw = jnp.asarray((rng.random((13, 301)) * 2).astype(np.float32))
    ref = contract_spec_grams(*args, row_weights=rw)
    pal = contract_spec_grams(*args, row_weights=rw, route="pallas",
                              block_n=128, interpret=True)
    _stats_close(ref, pal, rtol=1e-6, counts_exact=False)
    # Σw counts still agree to f32 rounding
    np.testing.assert_allclose(np.asarray(pal.n), np.asarray(ref.n),
                               rtol=1e-6)


def test_pallas_matches_xla_f64_ulp_level():
    if not jax.config.jax_enable_x64:
        pytest.skip("x64 parity configuration not enabled")
    args = _panel(dtype=np.float64)
    # matched blocking (firm_chunk == block_n) — the residual diffs are
    # reduction-order ulps inside XLA's differently-blocked dots
    ref = contract_spec_grams(*args, firm_chunk=128)
    pal = contract_spec_grams(*args, route="pallas", block_n=128,
                              interpret=True)
    _stats_close(ref, pal, rtol=1e-13)


def test_bf16_routes_agree_and_counts_exact():
    args = _panel(seed=7)
    ref = contract_spec_grams(*args)
    b_xla = contract_spec_grams(*args, precision="bf16")
    b_pal = contract_spec_grams(*args, precision="bf16", route="pallas",
                                block_n=128, interpret=True)
    # bf16 stats are stored f32 and counts are EXACT (f32 accumulation of
    # bf16-exact 0/1 products)
    assert np.asarray(b_xla.gram).dtype == np.float32
    np.testing.assert_array_equal(np.asarray(b_xla.n), np.asarray(ref.n))
    np.testing.assert_array_equal(np.asarray(b_pal.n), np.asarray(ref.n))
    _stats_close(b_xla, b_pal, rtol=1e-6)
    # and the bf16 grams sit at bf16 distance from the exact route — close
    # but not equal (the route really runs at reduced precision)
    d = np.max(np.abs(np.asarray(b_xla.gram) - np.asarray(ref.gram)))
    scale = np.max(np.abs(np.asarray(ref.gram)))
    assert 1e-7 < d / scale < 3e-2


def test_bf16_promotion_discloses_and_referees():
    """An ill-conditioned spec under bf16 is flagged per month and promoted
    (re-solved) by the full-precision QR referee."""
    from fm_returnprediction_tpu.specgrid.solve import run_spec_grid
    from fm_returnprediction_tpu.specgrid.specs import Spec, SpecGrid

    rng = np.random.default_rng(2)
    t, n = 6, 160
    base = rng.standard_normal((t, n)).astype(np.float32)
    x = np.stack([base, base + 1e-3 * rng.standard_normal((t, n)).astype(np.float32)],
                 axis=-1)                       # nearly collinear pair
    y = (base + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    masks = {"all": np.ones((t, n), bool)}
    grid = SpecGrid((Spec("m", ("c0", "c1"), "all"),), union=("c0", "c1"))

    exact = run_spec_grid(y, x, masks, grid, precision="highest",
                          gram_route="xla")
    low = run_spec_grid(y, x, masks, grid, precision="bf16",
                        gram_route="xla")
    # the collinear pair's equilibrated condition blows past 1/√eps(bf16):
    # every month is flagged, disclosed, and the spec re-solved by the QR
    # referee — landing on the incumbent full-precision answer
    assert int(low.suspect_months[0]) == t
    assert low.referee_specs == (0,)
    np.testing.assert_allclose(low.coef[0], exact.coef[0], rtol=5e-3)
    # a well-conditioned panel promotes nothing
    ok = run_spec_grid(y, np.stack([base, rng.standard_normal((t, n)).astype(np.float32)], -1),
                       masks, grid, precision="bf16", gram_route="xla")
    assert int(ok.suspect_months[0]) == 0
    assert ok.referee_specs == ()


def test_bf16_rejected_on_mesh():
    from fm_returnprediction_tpu.specgrid.solve import run_spec_grid
    from fm_returnprediction_tpu.specgrid.specs import Spec, SpecGrid

    grid = SpecGrid((Spec("m", ("c0",), "all"),), union=("c0",))
    with pytest.raises(ValueError, match="bf16"):
        run_spec_grid(np.zeros((3, 8), np.float32),
                      np.zeros((3, 8, 1), np.float32),
                      {"all": np.ones((3, 8), bool)}, grid,
                      precision="bf16", mesh=object())


def test_route_and_precision_resolution(monkeypatch):
    monkeypatch.delenv("FMRP_GRAM_ROUTE", raising=False)
    monkeypatch.delenv("FMRP_GRAM_PRECISION", raising=False)
    platform = jax.devices()[0].platform
    assert resolve_gram_route() == ("pallas" if platform == "tpu" else "xla")
    monkeypatch.setenv("FMRP_GRAM_ROUTE", "pallas")
    assert resolve_gram_route() == "pallas"
    monkeypatch.setenv("FMRP_GRAM_ROUTE", "xla")
    assert resolve_gram_route() == "xla"
    assert resolve_gram_route("pallas") == "pallas"  # arg beats env
    with pytest.raises(ValueError):
        resolve_gram_route("mxu")
    assert resolve_gram_precision() == "highest"
    monkeypatch.setenv("FMRP_GRAM_PRECISION", "bf16")
    assert resolve_gram_precision() == "bf16"
    with pytest.raises(ValueError):
        resolve_gram_precision("fp8")


def test_default_jaxpr_byte_identical():
    """The knobs at their defaults trace the exact historical program: an
    explicit route='xla', precision='highest' call and a no-kwarg call
    produce byte-identical jaxprs (no stray casts, no
    preferred_element_type markers)."""
    args = _panel(t=5, n=64, p=3, s=2)
    legacy = str(jax.make_jaxpr(
        lambda *a: contract_spec_grams(*a)
    )(*args))
    explicit = str(jax.make_jaxpr(
        lambda *a: contract_spec_grams(*a, route="xla", precision="highest")
    )(*args))
    assert legacy == explicit
    assert "bf16" not in legacy and "bfloat16" not in legacy


def test_grid_program_jaxpr_stable_across_knob_spelling():
    """The fused grid program's jaxpr is identical whether the knobs come
    from the environment or explicit arguments (telemetry/guard off)."""
    from fm_returnprediction_tpu.specgrid.solve import _spec_grid_program

    y, x, universes, uidx, col_sel, window = _panel(t=5, n=64, p=3, s=2)
    kw = dict(nw_lags=2, min_months=2, weights=("reference",),
              firm_chunk=None, guard=False)
    a = str(jax.make_jaxpr(
        lambda *ar: _spec_grid_program(*ar, **kw)
    )(y, x, universes, uidx, col_sel, window))
    b = str(jax.make_jaxpr(
        lambda *ar: _spec_grid_program(
            *ar, **kw, gram_route="xla", precision="highest")
    )(y, x, universes, uidx, col_sel, window))
    assert a == b
