"""Distributed observability plane (ISSUE 20).

Evidence in four layers, cheapest first:

- the WIRE: ``trace_env`` round-trips the router's span context into a
  child env and back into child root spans; the shm/socket frame header
  carries ``(t_send_ns, trace_id, parent_span)`` stamps when armed and
  all-zeros when not; ``record_span`` turns cross-process stamp pairs
  into spans and no-ops on unarmed peers.
- the AGGREGATOR: child registry deltas fold under ``{proc=}`` labels,
  a departed proc's monotone series land in ``proc="departed"`` so
  fleet totals NEVER move backwards across a kill+respawn, and every
  read/write shares ``SNAPSHOT_LOCK`` — a scrape can never tear.
- the ANNEX: a double-buffered commit-last shm mailbox whose previous
  mirror survives a SIGKILL landing exactly between the payload write
  and the commit flip — 30/30 deterministic chaos rounds on real
  processes; garbage harvests as absent, never as an exception.
- the TIMELINE: per-process exports re-anchor onto the router's clock
  EXACTLY, merge into one Perfetto document deterministically, and the
  per-hop table attributes e2e latency with a router-side share — plus
  the regress sentinel's disabled-section disclosure (skipped, never
  missing, never gated).

The slow tier drives a REAL process fleet on both transports for
span-propagation parity, and a chaos SIGKILL round for the controller's
flight-attached verdict + scrape monotonicity.
"""

import json
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from fm_returnprediction_tpu import telemetry
from fm_returnprediction_tpu.parallel.shm import shm_available
from fm_returnprediction_tpu.telemetry import distributed as obs
from fm_returnprediction_tpu.telemetry import regress
from fm_returnprediction_tpu.telemetry import spans
from fm_returnprediction_tpu.telemetry import timeline

pytestmark = pytest.mark.obs

_SHM = pytest.mark.skipif(not shm_available(),
                          reason="POSIX shared memory unavailable here")


@pytest.fixture(autouse=True)
def _clean_plane():
    telemetry.reset()
    telemetry.set_trace_dir(None)
    spans.set_remote_context(None)
    obs.clear_peers()
    obs.reset_delta_state()
    yield
    telemetry.reset()
    telemetry.set_trace_dir(None)
    spans.set_remote_context(None)
    obs.clear_peers()
    obs.reset_delta_state()


# -- trace context propagation ----------------------------------------------


def test_trace_env_roundtrips_into_child_root_spans(monkeypatch):
    monkeypatch.delenv("FMRP_TELEMETRY", raising=False)
    monkeypatch.delenv("FMRP_TRACE_DIR", raising=False)
    assert obs.trace_env() == {}  # unarmed spawn ships nothing

    monkeypatch.setenv("FMRP_TELEMETRY", "1")
    with spans.enabled(True):
        with telemetry.span("router.spawn") as s:
            env = obs.trace_env({"OTHER": "kept"})
        assert env["OTHER"] == "kept"
        assert env["FMRP_TELEMETRY"] == "1"
        assert env["FMRP_TRACE_REMOTE"] == f"{s.trace_id}:{s.span_id}"

        # child side: install → every ROOT span carries the remote parent
        got = obs.install_remote_context_from_env(
            {"FMRP_TRACE_REMOTE": env["FMRP_TRACE_REMOTE"]}
        )
        assert got == (s.trace_id, s.span_id)
        with telemetry.span("child.root") as root:
            with telemetry.span("child.nested") as nested:
                pass
        assert root.attrs["remote_trace"] == s.trace_id
        assert root.attrs["remote_parent"] == s.span_id
        assert "remote_trace" not in nested.attrs  # non-root: real parent
    # garbage never raises, never installs
    spans.set_remote_context(None)
    assert obs.install_remote_context_from_env(
        {"FMRP_TRACE_REMOTE": "not-a-context"}) is None


def test_frame_header_carries_trace_stamps_only_when_armed():
    from fm_returnprediction_tpu.serving import shm as fshm

    cold = fshm.pack_ack([7], [0])
    meta = fshm.frame_meta(cold)
    assert meta["kind"] == fshm.KIND_ACK and meta["count"] == 1
    assert (meta["t_send_ns"], meta["trace_id"], meta["parent_span"]) \
        == (0, 0, 0)

    with spans.enabled(True):
        with telemetry.span("router.request") as s:
            hot = fshm.pack_ack([7], [0])
    meta = fshm.frame_meta(hot)
    assert meta["t_send_ns"] > 0
    assert meta["trace_id"] == s.trace_id
    assert meta["parent_span"] == s.span_id
    # unpack_frame stays a row decoder — stamps are frame_meta's concern
    assert fshm.unpack_frame(hot)[0] == fshm.KIND_ACK


def test_record_span_from_explicit_stamps():
    assert spans.record_span("hop.x", 123) is None  # unarmed: no-op
    with spans.enabled(True):
        assert spans.record_span("hop.x", 0) is None  # unstamped peer
        s = spans.record_span("hop.transport_req", 1000, 2000, req=7)
        assert (s.t0_ns, s.t1_ns, s.attrs["req"]) == (1000, 2000, 7)
    assert [x.name for x in spans.finished_spans()] \
        == ["hop.transport_req"]


def test_peer_registry_records_clock_offsets(tmp_path):
    entry = obs.register_peer(
        "r0", pid=123, anchor_ns=spans.EPOCH_ANCHOR_NS + 5000,
        kind="replica",
    )
    assert entry["offset_ns"] == 5000
    assert obs.peers()["r0"]["pid"] == 123
    doc = json.loads(obs.dump_peers(tmp_path).read_text())
    assert doc["router_anchor_ns"] == spans.EPOCH_ANCHOR_NS
    assert doc["peers"]["r0"]["offset_ns"] == 5000


# -- metric aggregation ------------------------------------------------------


def test_registry_delta_ships_only_what_moved():
    c = telemetry.registry().counter("fmrp_obstest_deltas_total")
    c.inc(3)
    first = obs.registry_delta()
    assert first["fmrp_obstest_deltas_total"] == 3
    assert "fmrp_obstest_deltas_total" not in obs.registry_delta()
    c.inc(2)
    assert obs.registry_delta()["fmrp_obstest_deltas_total"] == 5


def test_aggregator_totals_monotone_across_kill_and_respawn():
    agg = obs.MetricAggregator()
    # bools coerce, NaN drops — ingest reports what it accepted
    assert agg.ingest("r0", {"fmrp_req_total": 5.0,
                             "fmrp_queue_depth": 3.0,
                             "fmrp_up": True,
                             "bad": float("nan")}) == 3
    agg.ingest("r1", {"fmrp_req_total": 2.0,
                      "fmrp_lat_seconds_sum{bucket=b16}": 0.5})
    assert agg.procs() == ("r0", "r1")
    snap = agg.snapshot()
    assert snap["fmrp_req_total{proc=r0}"] == 5.0
    assert snap["fmrp_lat_seconds_sum{bucket=b16,proc=r1}"] == 0.5
    before = agg.totals()
    assert before["fmrp_req_total"] == 7.0

    # r0 dies: monotone series fold into proc=departed, gauges vanish
    agg.fold_dead("r0")
    snap = agg.snapshot()
    assert "fmrp_req_total{proc=r0}" not in snap
    assert "fmrp_queue_depth{proc=r0}" not in snap  # gauge: not folded
    assert snap["fmrp_req_total{proc=departed}"] == 5.0
    assert agg.totals()["fmrp_req_total"] == 7.0  # nothing went backwards

    # the replacement counts up from zero under a NEW label
    agg.ingest("r2", {"fmrp_req_total": 1.0})
    after = agg.totals()
    for key, val in before.items():
        assert after[key] >= val, (key, val, after[key])
    assert after["fmrp_req_total"] == 8.0
    # double fold is idempotent; unknown proc is a no-op
    agg.fold_dead("r0")
    agg.fold_dead("never-lived")
    assert agg.totals()["fmrp_req_total"] == 8.0

    text = agg.prometheus_text()
    assert 'fmrp_req_total{proc="departed"} 5.0' in text
    assert 'fmrp_lat_seconds_sum{bucket="b16",proc="r1"} 0.5' in text
    assert "# TYPE" not in text  # untyped: the router registry declares


def test_scrape_and_ingest_serialize_on_the_snapshot_lock():
    from fm_returnprediction_tpu.telemetry import metrics as _metrics

    agg = obs.MetricAggregator()
    agg.ingest("r0", {"fmrp_req_total": 1.0})
    done = threading.Event()

    with _metrics.SNAPSHOT_LOCK:  # a scrape's whole-exposition hold
        t = threading.Thread(
            target=lambda: (agg.ingest("r0", {"fmrp_req_total": 2.0}),
                            done.set()),
        )
        t.start()
        time.sleep(0.1)
        # the concurrent delta is parked OUTSIDE the scrape's instant...
        assert not done.is_set()
        # ...while our own nested reads re-enter (RLock): one lock hold
        # can render registry + aggregator as one consistent snapshot
        assert agg.snapshot()["fmrp_req_total{proc=r0}"] == 1.0
    t.join(timeout=5)
    assert done.is_set()
    assert agg.snapshot()["fmrp_req_total{proc=r0}"] == 2.0


def test_build_info_gauge_in_exposition():
    text = telemetry.prometheus_text()
    (line,) = [l for l in text.splitlines()
               if l.startswith("fmrp_build_info{")]
    assert line.endswith(" 1")
    assert 'jax="' in line and 'backend="' in line
    assert "# TYPE fmrp_build_info gauge" in text


# -- flight annex ------------------------------------------------------------


@_SHM
def test_annex_mirror_harvest_roundtrip():
    annex = obs.FlightAnnex.create("t-roundtrip", nbytes=2048)
    try:
        assert annex.harvest() is None  # nothing committed yet
        assert annex.mirror({"type": "flight", "n": 1})
        assert annex.harvest() == {"type": "flight", "n": 1}
        assert annex.mirror({"type": "flight", "n": 2})  # other slot
        assert annex.harvest() == {"type": "flight", "n": 2}
        # an oversized payload is refused; the last mirror stays whole
        assert not annex.mirror({"blob": "x" * 4096})
        assert annex.harvest() == {"type": "flight", "n": 2}
        # mirror_flight sheds weight until the snapshot fits the slot
        assert annex.mirror_flight("test", max_spans=4)
        got = annex.harvest()
        assert got["type"] == "flight" and got["reason"] == "test"
    finally:
        annex.release()


_ANNEX_CHILD = r"""
import json, sys
from fm_returnprediction_tpu.resilience import FaultPlan, FaultSpec
from fm_returnprediction_tpu.telemetry.distributed import (
    ANNEX_MIRROR_SITE, FlightAnnex,
)

spec = json.loads(sys.argv[1])
annex = FlightAnnex.attach(spec)
assert annex.mirror({"type": "flight", "round": spec["round"],
                     "payload": "survivor"})
# the bomb: SIGKILL exactly between the payload write and the commit
# flip of the NEXT mirror — the torn write must read as absent
FaultPlan({ANNEX_MIRROR_SITE: FaultSpec(times=1, sigkill=True)}).__enter__()
annex.mirror({"type": "flight", "round": spec["round"], "payload": "torn"})
sys.exit(3)  # unreachable: the site above must have killed us
"""


@_SHM
@pytest.mark.timeout(300)
def test_annex_survives_sigkill_midwrite_30x():
    """30/30: a child SIGKILLed at ``obs.annex_mirror`` — after the new
    payload bytes are down but BEFORE the active-slot flip — leaves the
    PREVIOUS mirror harvestable, never a torn one."""
    for i in range(30):
        annex = obs.FlightAnnex.create(f"chaos{i}", nbytes=2048)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _ANNEX_CHILD,
                 json.dumps({**annex.describe(), "round": i})],
                capture_output=True, text=True, timeout=60,
            )
            assert proc.returncode == -signal.SIGKILL, \
                (i, proc.returncode, proc.stderr)
            assert annex.harvest() == {
                "type": "flight", "round": i, "payload": "survivor",
            }, i
        finally:
            annex.release()


# -- timeline merge + per-hop attribution ------------------------------------


def _write_export(path, anchor_ns, pid, proc_index, span_rows):
    meta = {"type": "meta", "schema": 1, "pid": pid, "anchor_ns": anchor_ns,
            "spans": len(span_rows), "events": 0, "dropped": 0}
    if proc_index is not None:
        meta["process_index"] = proc_index
    recs = [meta]
    for n, (name, ts_us, dur_us) in enumerate(span_rows, start=1):
        recs.append({"type": "span", "name": name, "cat": "hop",
                     "ts_us": ts_us, "dur_us": dur_us, "trace_id": 1,
                     "span_id": n, "parent_id": None, "thread_id": 1,
                     "thread_name": "main", "attrs": {}})
    path.write_text("\n".join(json.dumps(r, sort_keys=True) for r in recs)
                    + "\n")


def test_merge_realigns_child_clocks_exactly_and_deterministically(tmp_path):
    a_router, a_child = 2_000_000_000, 1_500_000_000
    _write_export(tmp_path / "events.jsonl", a_router, 100, None,
                  [("fleet.request", 1000.0, 10_000.0),
                   ("hop.admit", 1000.0, 2_000.0),
                   ("hop.complete", 9000.0, 1_000.0)])
    _write_export(tmp_path / "events.p0.jsonl", a_child, 200, 0,
                  [("hop.solve", 500.0, 5_000.0)])

    path, doc = timeline.merge_traces(tmp_path)
    assert path == tmp_path / "timeline.json"
    rows = {e["args"]["name"] for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"}
    assert rows == {"fmrp-router", "fmrp-child[p0]"}
    solve = [e for e in doc["traceEvents"]
             if e["ph"] == "X" and e["name"] == "hop.solve"]
    # exact re-anchor: ts + (anchor_router - anchor_child)/1e3
    assert solve[0]["ts"] == 500.0 + (a_router - a_child) / 1e3
    admit = [e for e in doc["traceEvents"]
             if e["ph"] == "X" and e["name"] == "hop.admit"]
    assert admit[0]["ts"] == 1000.0  # the router IS the anchor

    first = path.read_bytes()
    timeline.merge_traces(tmp_path)
    assert path.read_bytes() == first  # re-merge is byte-identical


def test_analyze_attributes_hop_shares_and_router_ceiling(tmp_path):
    _write_export(tmp_path / "events.jsonl", 0, 100, None,
                  [("fleet.request", 0.0, 10_000.0),
                   ("hop.admit", 0.0, 2_000.0),
                   ("hop.complete", 0.0, 1_000.0)])
    _write_export(tmp_path / "events.p0.jsonl", 0, 200, 0,
                  [("hop.solve", 0.0, 5_000.0)])
    journal = tmp_path / "journal.jsonl"
    journal.write_text(json.dumps({"ev": "admit", "req": 1, "seq": 1})
                       + "\n" + json.dumps({"ev": "done", "req": 1,
                                            "seq": 2}) + "\n")

    report = timeline.analyze(tmp_path, journal_path=journal)
    assert report["processes"] == 2 and report["requests"] == 1
    assert report["e2e_p50_ms"] == 10.0
    assert report["hops"]["hop.solve"]["share_pct"] == 50.0
    assert report["attributed_pct"] == 80.0
    assert report["router_share_pct"] == 30.0  # admit + complete
    assert report["journal"] == {"admit": 1, "done": 1}
    table = timeline.format_table(report)
    assert "hop.solve" in table and "router hops 30.0%" in table

    assert timeline.main(["-", str(tmp_path)]) == 0
    empty = tmp_path / "empty"
    empty.mkdir()
    assert timeline.main(["-", str(empty)]) == 2  # no e2e coverage


# -- journal timestamps (opt-in) ---------------------------------------------


def test_journal_t_ns_is_opt_in(tmp_path, monkeypatch):
    from fm_returnprediction_tpu.serving.journal import RequestJournal

    monkeypatch.delenv("FMRP_OBS_JOURNAL_TS", raising=False)
    with RequestJournal(tmp_path / "off.jsonl") as j:
        j.append("admit", 1)
        j.append("done", 1)
    recs = [json.loads(l) for l in
            (tmp_path / "off.jsonl").read_text().splitlines()]
    assert all("t_ns" not in r for r in recs)  # default: bytes stay
    # deterministic for the replay/recovery differential tests
    monkeypatch.setenv("FMRP_OBS_JOURNAL_TS", "1")
    with RequestJournal(tmp_path / "on.jsonl") as j:
        j.append("admit", 1)
    (rec,) = [json.loads(l) for l in
              (tmp_path / "on.jsonl").read_text().splitlines()]
    assert isinstance(rec["t_ns"], int) and rec["t_ns"] > 0


# -- regress: disabled-section disclosure ------------------------------------


def test_regress_disabled_sections_skip_not_missing(tmp_path):
    why = "FMRP_BENCH_FLEET=0 (deliberately disabled this round)"
    r1 = {"metric": "wall_s", "value": 10.0,
          "extra": {"fleet_p50_ms": 1.2, "other_p50_ms": 2.0,
                    "device": "cpu"}}
    r2 = {"metric": "wall_s", "value": 10.0,
          "extra": {"fleet": {"disabled": why}, "device": "cpu"}}
    p1, p2 = tmp_path / "BENCH_r01.json", tmp_path / "BENCH_r02.json"
    p1.write_text(json.dumps({"n": 1, "parsed": r1}))
    p2.write_text(json.dumps({"n": 2, "parsed": r2}))

    rounds = regress.load_rounds([p1, p2])
    assert rounds[-1].disabled == {"fleet": why}
    report = regress.analyze(rounds)
    # series keys are device-qualified; _disabled_why matches the bare key
    verdicts = {v.key.split("@", 1)[0]: v for v in report.verdicts}
    # under the disabled section: disclosed absence, never a finding
    assert verdicts["fleet_p50_ms"].status == "skipped"
    assert why in verdicts["fleet_p50_ms"].note
    # NOT under it: absence is still the "missing" finding it always was
    assert verdicts["other_p50_ms"].status == "missing"
    assert dict(report.disabled) == {"fleet": why}
    assert report.to_json()["disabled"] == {"fleet": why}
    text = report.format_text()
    assert why in text and "never gated" in text


# -- the real fleet: parity, harvest, monotone scrape (slow tier) ------------


def _tiny_state(rng, t=36, n=60, p=4):
    from fm_returnprediction_tpu.serving import build_serving_state

    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = (rng.standard_normal(p) * 0.05).astype(np.float32)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(y, x, mask, window=18, min_periods=9)
    months = np.nonzero(state.have_coef())[0]
    return state, months


def _await_exports(trace_dir, n, budget_s=20.0):
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        if len(list(trace_dir.glob("events*.jsonl"))) >= n:
            return
        time.sleep(0.1)
    pytest.fail(f"never saw {n} exports in {trace_dir}: "
                f"{sorted(p.name for p in trace_dir.glob('*'))}")


@pytest.mark.slow
@_SHM
@pytest.mark.timeout(420)
def test_span_propagation_parity_shm_vs_socket(tmp_path, monkeypatch):
    """Both transports produce the SAME hop chain: router-side hops in
    the router export, child-side hops in the child exports, child root
    spans carrying the router's remote context — the span-propagation
    wire is transport-independent."""
    from fm_returnprediction_tpu.serving import ServingFleet

    rng = np.random.default_rng(11)
    state, months = _tiny_state(rng)
    qx = rng.standard_normal(4).astype(np.float32)
    seen = {}
    for transport in ("shm", "socket"):
        trace_dir = tmp_path / f"trace-{transport}"
        monkeypatch.setenv("FMRP_TELEMETRY", "1")
        monkeypatch.setenv("FMRP_TRACE_DIR", str(trace_dir))
        with telemetry.tracing(str(trace_dir)):
            # a span open at spawn time is what trace_env forwards as
            # the children's remote parent context
            with telemetry.span("fleet.spawn", transport=transport):
                fleet = ServingFleet(
                    state, 2, replica_mode="process", transport=transport,
                    journal=str(tmp_path / f"journal-{transport}.jsonl"),
                    max_batch=16, max_latency_ms=1.0,
                )
            try:
                futs = [fleet.submit(int(months[0]), qx)
                        for _ in range(16)]
                vals = [f.result(timeout=60) for f in futs]
                assert len(set(vals)) == 1 and np.isfinite(vals[0])
            finally:
                fleet.close()
        _await_exports(trace_dir, 3)  # router + both children flushed

        procs = timeline.load_process_traces(trace_dir)
        children = [p for p in procs
                    if p["meta"].get("process_index") is not None]
        assert len(children) == 2, [p["path"] for p in procs]
        by_side = {"router": set(), "child": set()}
        for p in procs:
            side = "child" if p in children else "router"
            for r in p["records"]:
                if r.get("type") == "span":
                    by_side[side].add(r["name"])
        wanted = set(timeline.HOPS) | {timeline.E2E_SPAN}
        seen[transport] = {side: names & wanted
                          for side, names in by_side.items()}
        # child roots carry the router's context as remote attrs
        assert any((r.get("attrs") or {}).get("remote_trace")
                   for p in children for r in p["records"]
                   if r.get("type") == "span")
        report = timeline.analyze(
            trace_dir,
            journal_path=tmp_path / f"journal-{transport}.jsonl")
        assert report["requests"] >= 16
        assert report["attributed_pct"] > 0
        telemetry.reset()

    assert seen["shm"] == seen["socket"], seen
    assert timeline.E2E_SPAN in seen["shm"]["router"]
    assert "hop.admit" in seen["shm"]["router"]
    assert "hop.solve" in seen["shm"]["child"]


@pytest.mark.slow
@_SHM
@pytest.mark.timeout(420)
def test_chaos_sigkill_flight_harvest_and_monotone_scrape(tmp_path,
                                                          monkeypatch):
    """A replica SIGKILLed mid-result-send: its flight annex harvests
    through the kill, the controller attaches it to the respawn verdict
    and journal mark, and the fleet's /metrics totals never move
    backwards across the kill + respawn."""
    from fm_returnprediction_tpu.resilience import FaultPlan, FaultSpec
    from fm_returnprediction_tpu.serving import ServingFleet
    from fm_returnprediction_tpu.topology import (
        TopologyController,
        TopologySpec,
    )

    monkeypatch.setenv("FMRP_OBS_ANNEX", "1")
    rng = np.random.default_rng(13)
    state, months = _tiny_state(rng)
    journal = tmp_path / "journal.jsonl"
    spec = TopologySpec(replicas=2, replica_mode="process",
                        transport="shm")
    # shm results leave through a ring commit, so the SIGKILL site is
    # the commit seam (the socket flavor would be replica.result_send)
    with FaultPlan({"shm.ring.commit":
                    FaultSpec(times=1, sigkill=True, proc="0")}):
        fleet = ServingFleet(state, 2, replica_mode="process",
                             transport="shm", journal=str(journal),
                             registry_dir=str(tmp_path / "registry"),
                             max_batch=16, max_latency_ms=2.0)
    ctl = TopologyController(spec, fleet=fleet, ping_timeout_s=1.0)
    try:
        # prime the aggregator: a stats probe ships each child's first
        # (full) registry delta before anything dies
        for rid in list(fleet.replica_states()):
            try:
                fleet.replica(rid).service.stats()
            except Exception:  # noqa: BLE001 — victim may already be down
                pass
        qx = rng.standard_normal(4).astype(np.float32)
        futs = [fleet.submit(int(months[0]), qx) for _ in range(8)]
        vals = [f.result(timeout=60) for f in futs]
        assert len(set(vals)) == 1 and np.isfinite(vals[0])

        dead = [r for r, s in ctl.probe().items() if s != "live"]
        assert len(dead) == 1, dead
        victim = dead[0]
        before = fleet.aggregator.totals()
        (action,) = ctl.repair()
        assert action.startswith(f"respawn:{victim}")

        # the flight tail survived the SIGKILL and names its last act
        flight = ctl.flight(victim)
        assert flight is not None and flight["type"] == "flight"
        assert victim in fleet.flights
        marks = [json.loads(ln) for ln in
                 journal.read_text().splitlines() if ln.strip()]
        (respawn,) = [m for m in marks if m.get("ev") == "mark"
                      and m.get("label") == "respawn"]
        assert str(respawn.get("flight", "")).startswith("flight=")

        # respawned world ships again; fleet totals stay monotone
        for rid in list(fleet.replica_states()):
            fleet.replica(rid).service.stats()
        after = fleet.aggregator.totals()
        for key, val in before.items():
            assert after.get(key, 0.0) >= val - 1e-9, (key, val)

        text = fleet.prometheus_metrics()
        assert "fmrp_build_info{" in text
        assert 'proc="departed"' in text  # the fold is IN the scrape
    finally:
        ctl.close()
    assert ctl.sweep() == {"segments": [], "fds": []}
