"""Rolling/compaction/quantile ops vs pandas ground truth.

pandas IS the semantics oracle here: the reference's characteristic kernels
are pandas ``groupby.shift``/``rolling``/``percentile`` calls, and 1e-4
parity hinges on matching their row-based window rules exactly (SURVEY §7
hard part (b))."""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.ops import (
    compact,
    lag,
    make_compaction,
    masked_quantile,
    rolling_mean,
    rolling_prod,
    rolling_std,
    rolling_sum,
    scatter_back,
    winsorize_cs,
)


@pytest.fixture(scope="module")
def ragged():
    """(T, N) values + mask with gaps and NaNs, plus the equivalent long frame."""
    rng = np.random.default_rng(11)
    T, N = 60, 25
    values = rng.normal(size=(T, N))
    values[rng.random((T, N)) < 0.1] = np.nan  # missing value, row present
    mask = rng.random((T, N)) > 0.15           # row absent entirely
    months = pd.date_range("1990-01-31", periods=T, freq="ME")
    t_idx, n_idx = np.nonzero(mask)
    df = pd.DataFrame(
        {"permno": n_idx, "mthcaldt": months[t_idx], "x": values[t_idx, n_idx]}
    ).sort_values(["permno", "mthcaldt"])
    return values, mask, df


def _pandas_groupby_apply(df, fn):
    out = fn(df.groupby("permno")["x"])
    if isinstance(out.index, pd.MultiIndex):  # rolling ops prepend the group key
        out = out.reset_index(level=0, drop=True)
    return df.assign(out=out)


def _compare(ragged, device_out, pandas_df):
    """Compare a (T, N) device result against the long pandas result."""
    values, mask, _ = ragged
    got = np.asarray(device_out)
    for _, row in pandas_df.iterrows():
        t = (pd.Timestamp(row["mthcaldt"]).year - 1990) * 12 + (
            pd.Timestamp(row["mthcaldt"]).month - 1
        )
        n = int(row["permno"])
        want = row["out"]
        if np.isnan(want):
            assert np.isnan(got[t, n]), (t, n, got[t, n])
        else:
            np.testing.assert_allclose(got[t, n], want, rtol=1e-10, err_msg=f"{t},{n}")


def test_lag_matches_groupby_shift(ragged):
    values, mask, df = ragged
    plan = make_compaction(jnp.asarray(mask))
    comp = compact(jnp.asarray(values), plan)
    out = scatter_back(lag(comp, 2), plan)
    expect = _pandas_groupby_apply(df, lambda g: g.shift(2))
    _compare(ragged, out, expect)


def test_rolling_sum_matches_pandas(ragged):
    values, mask, df = ragged
    plan = make_compaction(jnp.asarray(mask))
    comp = jnp.where(plan.valid, compact(jnp.asarray(values), plan), jnp.nan)
    out = scatter_back(rolling_sum(comp, 12, 1), plan)
    expect = _pandas_groupby_apply(
        df, lambda g: g.rolling(window=12, min_periods=1).sum()
    )
    _compare(ragged, out, expect)


def test_rolling_std_matches_pandas(ragged):
    values, mask, df = ragged
    plan = make_compaction(jnp.asarray(mask))
    comp = jnp.where(plan.valid, compact(jnp.asarray(values), plan), jnp.nan)
    out = scatter_back(rolling_std(comp, 10, 4), plan)
    expect = _pandas_groupby_apply(
        df, lambda g: g.rolling(window=10, min_periods=4).std()
    )
    _compare(ragged, out, expect)


def test_rolling_prod_matches_pandas(ragged):
    values, mask, df = ragged
    plan = make_compaction(jnp.asarray(mask))
    gross = 1.0 + 0.1 * jnp.where(plan.valid, compact(jnp.asarray(values), plan), jnp.nan)
    out = scatter_back(rolling_prod(gross, 11, 11), plan)
    df2 = df.assign(x=1.0 + 0.1 * df["x"])
    expect = _pandas_groupby_apply(
        df2, lambda g: g.rolling(window=11, min_periods=11).apply(np.prod, raw=True)
    )
    _compare(ragged, out, expect)


def test_rolling_mean_matches_pandas(ragged):
    values, mask, df = ragged
    plan = make_compaction(jnp.asarray(mask))
    comp = jnp.where(plan.valid, compact(jnp.asarray(values), plan), jnp.nan)
    out = scatter_back(rolling_mean(comp, 24, 12), plan)
    expect = _pandas_groupby_apply(
        df, lambda g: g.rolling(window=24, min_periods=12).mean()
    )
    _compare(ragged, out, expect)


def test_masked_quantile_matches_numpy(ragged):
    values, mask, _ = ragged
    valid = mask & np.isfinite(values)
    got = np.asarray(
        masked_quantile(jnp.asarray(values), jnp.asarray(valid), jnp.asarray([0.2, 0.5]))
    )
    for t in range(values.shape[0]):
        vals = values[t][valid[t]]
        if len(vals) == 0:
            assert np.all(np.isnan(got[t]))
            continue
        np.testing.assert_allclose(got[t, 0], np.percentile(vals, 20), rtol=1e-12)
        np.testing.assert_allclose(got[t, 1], np.percentile(vals, 50), rtol=1e-12)


def test_masked_quantile_scalar_q(ragged):
    values, mask, _ = ragged
    valid = mask & np.isfinite(values)
    got = np.asarray(masked_quantile(jnp.asarray(values), jnp.asarray(valid), 0.5))
    assert got.shape == (values.shape[0],)


def test_winsorize_matches_reference_semantics(ragged):
    values, mask, _ = ragged
    valid = mask & np.isfinite(values)
    got = np.asarray(winsorize_cs(jnp.asarray(values), jnp.asarray(mask)))
    for t in range(values.shape[0]):
        vals = values[t][valid[t]]
        if len(vals) < 5:
            np.testing.assert_array_equal(got[t][mask[t]], values[t][mask[t]])
            continue
        lo, hi = np.percentile(vals, 1), np.percentile(vals, 99)
        want = np.clip(values[t], lo, hi)
        np.testing.assert_allclose(
            got[t][valid[t]], want[valid[t]], rtol=1e-12
        )


def test_winsorize_small_month_skipped():
    """Months with <5 valid obs pass through (src/calc_Lewellen_2014.py:520)."""
    values = np.array([[5.0, -3.0, 100.0, np.nan, np.nan, np.nan, np.nan, np.nan]])
    mask = np.ones_like(values, dtype=bool)
    got = np.asarray(winsorize_cs(jnp.asarray(values), jnp.asarray(mask)))
    np.testing.assert_array_equal(got[0, :3], values[0, :3])


def test_rolling_prod_nan_propagates_like_numpy_prod():
    """pandas rolling.apply(np.prod) yields NaN for any window containing NaN
    once min_periods is met — NaN must propagate, not be treated as 1."""
    x = np.array([1.1, np.nan, 1.2, 1.3, 1.4])
    got = np.asarray(rolling_prod(jnp.asarray(x)[:, None], 3, 2))[:, 0]
    want = (
        pd.Series(x).rolling(3, min_periods=2).apply(np.prod, raw=True).to_numpy()
    )
    np.testing.assert_allclose(got, want)


def test_rolling_route_honors_committed_placement(monkeypatch):
    """A CPU-committed array must route XLA even when the process's
    DEFAULT backend is TPU (simulated): the committed placement is read
    through the PUBLIC ``sharding.device_set`` API — a silent-None
    fallback (what the old private ``_device_assignment`` read would
    degrade to on a jax rename) would dispatch the TPU-only pallas
    kernel on a host-placed array."""
    import jax

    from fm_returnprediction_tpu.ops import rolling

    monkeypatch.delenv("FMRP_ROLLING_ROUTE", raising=False)
    monkeypatch.delenv("FMRP_PALLAS", raising=False)

    class _FakeTpu:
        platform = "tpu"

    x = jnp.ones((4, 4), jnp.float32)  # committed to this process's CPU
    assert rolling.resolve_rolling_route(x) == "xla"
    monkeypatch.setattr(jax, "devices", lambda *a: [_FakeTpu()])
    # default backend claims TPU, but the ARRAY is CPU-committed: the
    # placement must win (route stays xla)
    assert rolling.resolve_rolling_route(x) == "xla"
    # no committed placement (bare numpy): the default backend decides
    assert rolling.resolve_rolling_route(np.ones((4, 4))) == "pallas"
