"""Cache substrate tests (reference contract: ``src/utils.py:68-329``)."""

import zipfile

import pandas as pd
import pytest

from fm_returnprediction_tpu.utils import cache


@pytest.fixture
def frame():
    return pd.DataFrame({"permno": [1, 2, 3], "retx": [0.01, -0.02, 0.03]})


def test_flatten_dict_to_str():
    out = cache.flatten_dict_to_str(
        {"ticker": ["AAPL", "MSFT"], "date": {"gte": "2020-01-01"}}
    )
    assert out == "ticker=['AAPL', 'MSFT'],date.gte=2020-01-01"


def test_cache_filename_shape(tmp_path):
    paths = cache.cache_filename(
        "crsp/msf_v2", "start_date=1964-01-01,end_date=2013-12-31", tmp_path
    )
    assert [p.suffix for p in paths] == [".parquet", ".csv", ".zip"]
    assert all(p.name.startswith("crsp_msf_v2__") for p in paths)
    # date components survive sanitization
    assert "19640101" in paths[0].name


def test_hash_cache_filename_stable(tmp_path):
    a = cache.hash_cache_filename("comp_funda", "vars=x,start_date=1964-01-01", tmp_path)
    b = cache.hash_cache_filename("comp_funda", "vars=x,start_date=1964-01-01", tmp_path)
    assert a == b
    c = cache.hash_cache_filename("comp_funda", "vars=y,start_date=1964-01-01", tmp_path)
    assert a != c  # different non-date filters hash differently


def test_roundtrip_parquet_and_csv(tmp_path, frame):
    for ext in ("parquet", "csv"):
        path = tmp_path / f"data.{ext}"
        cache.write_cache_data(frame, path)
        out = cache.read_cached_data(path)
        pd.testing.assert_frame_equal(out, frame, check_dtype=False)


def test_zip_roundtrip(tmp_path, frame):
    csv_path = tmp_path / "inner.csv"
    frame.to_csv(csv_path, index=False)
    zip_path = tmp_path / "data.zip"
    with zipfile.ZipFile(zip_path, "w") as archive:
        archive.write(csv_path, "inner.csv")
    out = cache.read_cached_data(zip_path)
    pd.testing.assert_frame_equal(out, frame, check_dtype=False)


def test_first_hit_wins(tmp_path, frame):
    paths = [tmp_path / "x.parquet", tmp_path / "x.csv"]
    assert cache.file_cached(paths) is None
    cache.write_cache_data(frame, paths[1])
    assert cache.file_cached(paths) == paths[1]
    cache.write_cache_data(frame, paths[0])
    assert cache.file_cached(paths) == paths[0]


def test_save_and_load_by_name(tmp_path, frame):
    path = cache.save_cache_data(frame, tmp_path, file_name="CRSP_stock_m")
    assert path.name == "CRSP_stock_m.parquet"
    out = cache.load_cache_data(tmp_path, "CRSP_stock_m.parquet")
    pd.testing.assert_frame_equal(out, frame, check_dtype=False)
    with pytest.raises(FileNotFoundError):
        cache.load_cache_data(tmp_path, "missing.parquet")


def test_hash_filename_keeps_dataset_code(tmp_path):
    """Distinct dataset codes with identical filters must never collide."""
    a = cache.hash_cache_filename(
        "crsp_msf_v2", "start_date=1964-01-01,end_date=2013-12-31", tmp_path
    )
    b = cache.hash_cache_filename(
        "crsp_dsf_v2", "start_date=1964-01-01,end_date=2013-12-31", tmp_path
    )
    assert a != b
    assert a[0].name.startswith("crsp_msf_v2_")
    assert b[0].name.startswith("crsp_dsf_v2_")


def test_hash_filename_bracketed_date_list_kept_whole(tmp_path):
    paths = cache.hash_cache_filename(
        "q", "date=['2020-01-01', '2021-06-30'],ticker=AAPL", tmp_path
    )
    # both dates stay readable; ticker is folded into the hash
    assert "20200101" in paths[0].name and "20210630" in paths[0].name
    assert "AAPL" not in paths[0].name


def test_hash_filename_date_in_value_is_hashed(tmp_path):
    """'date' must appear in the KEY to stay readable, not in the value."""
    paths = cache.hash_cache_filename("q", "table=stkdatedelist,start_date=2020-01-01", tmp_path)
    assert "stkdatedelist" not in paths[0].name
    assert "20200101" in paths[0].name


def test_array_bundle_roundtrip_and_reserved_names(tmp_path):
    """Bundle arrays + meta roundtrip exactly; names that would collide
    with np.savez_compressed's own parameters (consumed as kwargs —
    TypeError for 'file', silently DROPPED for 'allow_pickle') are
    rejected up front instead of corrupting the bundle."""
    import numpy as np

    arrays = {"a": np.arange(6.0).reshape(2, 3), "b": np.array([True, False])}
    path = cache.save_array_bundle(tmp_path / "bundle", arrays, {"k": 1})
    got, meta = cache.load_array_bundle(path)
    assert meta == {"k": 1}
    for name in arrays:
        np.testing.assert_array_equal(got[name], arrays[name])
    for bad in ("file", "allow_pickle", "args", "kwds", "__meta__"):
        with pytest.raises(ValueError, match="reserved"):
            cache.save_array_bundle(tmp_path / "x", {bad: np.zeros(1)})
