"""Worker for the two-process TASKGRAPH test (test_multiprocess.py).

Drives the five-task pipeline DAG with 2 real `jax.distributed` processes
sharing one filesystem (the pod scenario):

- phase 1: both processes run the DAG from empty state — process 0 must
  write every artifact exactly once (``_primary_writes``), the barriers
  must release process 1 only after each write, and both must finish.
- phase 2: ASYMMETRIC staleness — process 0 keeps its state DB (all tasks
  locally up to date), process 1 starts a fresh DB (all tasks stale).
  Without the runner's cross-process consensus this deadlocks: process 1
  enters an action barrier process 0 never reaches. With consensus, both
  re-run everything and succeed.

Usage: python mp_taskgraph_worker.py <pid> <nprocs> <port> <workdir>
"""

import os
import sys
from pathlib import Path

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
workdir = Path(sys.argv[4])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

from fm_returnprediction_tpu.parallel.multihost import (  # noqa: E402
    initialize_multihost,
)

initialize_multihost(
    coordinator_address=f"localhost:{port}", num_processes=nprocs, process_id=pid
)

from jax.experimental import multihost_utils  # noqa: E402

from fm_returnprediction_tpu.data.synthetic import SyntheticConfig  # noqa: E402
from fm_returnprediction_tpu.taskgraph.engine import (  # noqa: E402
    PlainReporter,
    TaskRunner,
)
from fm_returnprediction_tpu.taskgraph.tasks import build_tasks  # noqa: E402

raw, processed, out = workdir / "raw", workdir / "processed", workdir / "out"
for d in (raw, processed, out):
    d.mkdir(parents=True, exist_ok=True)


def make_tasks():
    tasks = build_tasks(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=30, n_months=30),
        raw_dir=raw,
        processed_dir=processed,
        output_dir=out,
    )
    # drop the config task's global-dir action; dirs are created above
    tasks = [t for t in tasks if t.name != "config"]
    for t in tasks:
        t.task_dep = [d for d in t.task_dep if d != "config"]
    return tasks


db = workdir / f"state_p{pid}.sqlite"
with TaskRunner(make_tasks(), db_path=db, reporter=PlainReporter()) as r:
    assert r.run(), "phase-1 DAG run failed"
assert (out / "table_1.pkl").exists() and (processed / "lewellen_panel.npz").exists()

multihost_utils.sync_global_devices("phase2_setup")
if pid == 1:  # asymmetric staleness: process 1 forgets everything
    db.unlink()
multihost_utils.sync_global_devices("phase2_go")

with TaskRunner(make_tasks(), db_path=db, reporter=PlainReporter()) as r2:
    assert r2.run(), "phase-2 (asymmetric staleness) run failed"
assert (out / "table_1.pkl").exists()

# phase 3: ONE-SIDED failure must stop BOTH processes symmetrically (the
# engine's per-task success consensus) — without it, process 0 would march
# into the next collective and hang while process 1 holds the traceback.
multihost_utils.sync_global_devices("phase3_go")
from fm_returnprediction_tpu.taskgraph.engine import Task  # noqa: E402


def flaky():
    if pid == 1:
        raise RuntimeError("injected one-sided failure")


with TaskRunner(
    [Task("flaky", [flaky]), Task("after", [lambda: None], task_dep=["flaky"])],
    db_path=workdir / f"state3_p{pid}.sqlite", reporter=PlainReporter(),
) as r3:
    assert r3.run() is False, "one-sided failure must fail the run everywhere"

print(f"TG_OK {pid}", flush=True)
