"""Worker for the two-process TASKGRAPH test (test_multiprocess.py).

Drives the five-task pipeline DAG with 2 real processes sharing one
filesystem (the pod scenario):

- phase 1: both processes run the DAG from empty state — process 0 must
  write every artifact exactly once (``_primary_writes``), the barriers
  must release process 1 only after each write, and both must finish.
- phase 2: ASYMMETRIC staleness — process 0 keeps its state DB (all tasks
  locally up to date), process 1 starts a fresh DB (all tasks stale).
  Without the runner's cross-process consensus this deadlocks: process 1
  enters an action barrier process 0 never reaches. With consensus, both
  re-run everything and succeed.
- phase 3: a ONE-SIDED failure must stop both processes symmetrically.

Two transports (argv[5], the engine's fallback ladder):

- ``host``  — the ``FMRP_DIST_*`` bootstrap (``parallel.distributed``):
  barriers and consensus ride the host-side exchange, which answers on
  EVERY backend — this is the mode that runs for real on this
  container's CPU jaxlib (no device collectives needed anywhere).
- ``jax``   — ``jax.distributed`` device collectives via
  ``initialize_multihost`` (the pod path); on a CPU backend without
  cross-process collectives the first collective raises the named gap
  the parent test probes for.

Usage: python mp_taskgraph_worker.py <pid> <nprocs> <port> <workdir> <transport>
"""

import os
import sys
from pathlib import Path

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
workdir = Path(sys.argv[4])
transport = sys.argv[5] if len(sys.argv) > 5 else "jax"

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"

if transport == "host":
    os.environ["FMRP_DIST_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["FMRP_DIST_PROCS"] = str(nprocs)
    os.environ["FMRP_DIST_PROC_ID"] = str(pid)
    os.environ["FMRP_DIST_JAX"] = "0"

    from fm_returnprediction_tpu.parallel import distributed as dist

    assert dist.initialize_distributed() == (pid, nprocs)
    # idempotent second call must return the same coords
    assert dist.initialize_distributed() == (pid, nprocs)

    def sync(tag: str) -> None:
        dist.host_exchange().barrier(tag)

else:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    from fm_returnprediction_tpu.parallel.multihost import (
        initialize_multihost,
    )

    initialize_multihost(
        coordinator_address=f"localhost:{port}", num_processes=nprocs,
        process_id=pid,
    )

    def sync(tag: str) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


from fm_returnprediction_tpu.data.synthetic import SyntheticConfig  # noqa: E402
from fm_returnprediction_tpu.taskgraph.engine import (  # noqa: E402
    PlainReporter,
    TaskRunner,
)
from fm_returnprediction_tpu.taskgraph.tasks import build_tasks  # noqa: E402

raw, processed, out = workdir / "raw", workdir / "processed", workdir / "out"
for d in (raw, processed, out):
    d.mkdir(parents=True, exist_ok=True)


def make_tasks():
    tasks = build_tasks(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=30, n_months=30),
        raw_dir=raw,
        processed_dir=processed,
        output_dir=out,
    )
    # drop the config task's global-dir action; dirs are created above
    tasks = [t for t in tasks if t.name != "config"]
    for t in tasks:
        t.task_dep = [d for d in t.task_dep if d != "config"]
    return tasks


db = workdir / f"state_p{pid}.sqlite"
with TaskRunner(make_tasks(), db_path=db, reporter=PlainReporter()) as r:
    assert r.run(), "phase-1 DAG run failed"
assert (out / "table_1.pkl").exists() and (processed / "lewellen_panel.npz").exists()

sync("phase2_setup")
if pid == 1:  # asymmetric staleness: process 1 forgets everything
    db.unlink()
sync("phase2_go")

with TaskRunner(make_tasks(), db_path=db, reporter=PlainReporter()) as r2:
    assert r2.run(), "phase-2 (asymmetric staleness) run failed"
assert (out / "table_1.pkl").exists()

# phase 3: ONE-SIDED failure must stop BOTH processes symmetrically (the
# engine's per-task success consensus) — without it, process 0 would march
# into the next collective and hang while process 1 holds the traceback.
sync("phase3_go")
from fm_returnprediction_tpu.taskgraph.engine import Task  # noqa: E402


def flaky():
    if pid == 1:
        raise RuntimeError("injected one-sided failure")


with TaskRunner(
    [Task("flaky", [flaky]), Task("after", [lambda: None], task_dep=["flaky"])],
    db_path=workdir / f"state3_p{pid}.sqlite", reporter=PlainReporter(),
) as r3:
    assert r3.run() is False, "one-sided failure must fail the run everywhere"

print(f"TG_OK {pid}", flush=True)
