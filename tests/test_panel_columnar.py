"""Columnar-vs-legacy panel route differentials.

The columnar ingest (``panel.columnar`` over ``data.columnar``) replaces
the pandas relational chain with numpy searchsorted/gather joins over
chunked Arrow reads. Its contract is EXACT equality with the legacy route
wherever the legacy path is exact — pinned here at every level:

- the dense BASE panel (values, mask, month/firm vocabularies) bit-equal;
- the enriched characteristic panel bit-equal (both routes share the same
  fused device program, so host ingest is the only possible divergence);
- Table 1/2, the figure sweep cross-sections, the decile table and the
  serving-state artifacts bit-equal through ``run_pipeline``;
- edge cases: thin months (< 5 valid rows, the winsorize skip path) and
  an all-NaN fundamental column survive both routes identically;
- the ``FMRP_PANEL_ROUTE`` knob selects routes, rejects junk, and a
  ``ColumnarIngestError`` falls back to legacy with a warning;
- the prepared-inputs checkpoint v3 (columnar mmap payloads) round-trips
  under full-hash verification and detects payload corruption.
"""

import os
import shutil

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.synthetic import (
    FILE_NAMES,
    SyntheticConfig,
    write_synthetic_cache,
)
from fm_returnprediction_tpu.panel.columnar import build_panel_columnar
from fm_returnprediction_tpu.pipeline import (
    build_panel,
    load_or_build_panel,
    load_raw_data,
    panel_route,
    run_pipeline,
)

CFG = SyntheticConfig(n_firms=40, n_months=60)


@pytest.fixture(scope="module")
def raw_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("raw_columnar")
    write_synthetic_cache(d, CFG)
    return d


def _assert_panels_equal(a, b):
    assert a.var_names == b.var_names
    np.testing.assert_array_equal(np.asarray(a.months), np.asarray(b.months))
    np.testing.assert_array_equal(np.asarray(a.ids), np.asarray(b.ids))
    np.testing.assert_array_equal(np.asarray(a.mask), np.asarray(b.mask))
    va, vb = np.asarray(a.values), np.asarray(b.values)
    assert va.shape == vb.shape
    assert np.array_equal(va, vb, equal_nan=True), (
        "panel values differ between routes"
    )


def _routes_panels(raw, dtype=np.float64):
    legacy, f_l = build_panel(load_raw_data(raw), dtype=dtype)
    columnar, f_c = build_panel_columnar(raw, dtype=dtype)
    assert f_l == f_c
    return legacy, columnar


def test_enriched_panel_bit_equal(raw_dir):
    legacy, columnar = _routes_panels(raw_dir)
    _assert_panels_equal(legacy, columnar)


def test_compact_daily_bit_equal(raw_dir):
    """The chunked filtered daily ingest lands on the same CSR strips as
    the pandas filter + frame path."""
    import dataclasses

    from fm_returnprediction_tpu.data.wrds_pull import (
        subset_to_common_stock_and_exchanges,
    )
    from fm_returnprediction_tpu.panel.columnar import (
        ingest_compact_daily_columnar,
    )
    from fm_returnprediction_tpu.panel.daily import build_compact_daily

    data = load_raw_data(raw_dir)
    crsp_d = subset_to_common_stock_and_exchanges(
        data["crsp_d"], columns=["permno", "dlycaldt", "retx"]
    )
    months = np.unique(data["crsp_m"]["jdate"].to_numpy())
    cd_l = build_compact_daily(crsp_d, data["crsp_index_d"], months)
    cd_c = ingest_compact_daily_columnar(raw_dir, months)
    for field in dataclasses.fields(cd_l):
        a, b = getattr(cd_l, field.name), getattr(cd_c, field.name)
        if isinstance(a, np.ndarray):
            if a.dtype.kind == "M":
                a, b = a.astype("datetime64[s]"), b.astype("datetime64[s]")
            np.testing.assert_array_equal(a, b, err_msg=field.name)
        else:
            assert a == b, field.name


def test_thin_month_edge_case(tmp_path):
    """A universe small enough that months fall under the winsorize
    min_obs=5 skip threshold: both routes agree bit-for-bit."""
    raw = tmp_path / "thin"
    write_synthetic_cache(raw, SyntheticConfig(n_firms=6, n_months=30))
    legacy, columnar = _routes_panels(raw)
    _assert_panels_equal(legacy, columnar)


def test_all_nan_column_edge_case(tmp_path):
    """An all-NaN fundamental column (every dvc null → dy all-NaN) flows
    through ingest, winsorize and assembly identically on both routes."""
    raw = tmp_path / "nan_col"
    write_synthetic_cache(raw, SyntheticConfig(n_firms=25, n_months=36))
    comp_path = raw / FILE_NAMES["comp"]
    comp = pd.read_parquet(comp_path)
    comp["dvc"] = np.nan
    comp.to_parquet(comp_path, index=False)
    legacy, columnar = _routes_panels(raw)
    assert np.isnan(np.asarray(legacy.var("dy"))).all()
    _assert_panels_equal(legacy, columnar)


def test_multilink_and_multisecurity_edge_cases(tmp_path):
    """The join semantics the base fixture does not reach: (a) a permno
    with SEVERAL valid CCM links — the legacy route emits one merged row
    per link and `long_to_dense` keeps the last (the largest gvkey), which
    the columnar join must pick directly, in both directions (extra link
    above AND below the original gvkey); (b) four securities per
    (permco, jdate) incl. an exact security-ME tie — exercises the Kahan
    group sum beyond the 2-element case (where it degenerates to naive
    addition) and the min-permno tie-break."""
    raw = tmp_path / "links"
    write_synthetic_cache(raw, SyntheticConfig(n_firms=30, n_months=36))

    ccm_path = raw / FILE_NAMES["ccm"]
    ccm = pd.read_parquet(ccm_path)
    ccm = ccm.sort_values("gvkey").reset_index(drop=True)
    wide_lo = ccm.iloc[[0]].assign(permno=ccm["permno"].iloc[-1])
    wide_hi = ccm.iloc[[-1]].assign(permno=ccm["permno"].iloc[0])
    for extra in (wide_lo, wide_hi):
        extra["linkdt"] = pd.Timestamp("1960-01-31")
        extra["linkenddt"] = pd.NaT  # open link: valid through today
    pd.concat([ccm, wide_lo, wide_hi]).to_parquet(ccm_path, index=False)

    m_path = raw / FILE_NAMES["crsp_m"]
    m = pd.read_parquet(m_path)
    victim_permco = m["permco"].iloc[0]
    block = m[m["permco"] == victim_permco]
    clones = []
    for i, scale in enumerate((0.31, 0.57, 1.0)):  # last: exact ME tie
        c = block.copy()
        c["permno"] = c["permno"] + 90_000 + i
        c["prc"] = c["prc"] * scale
        clones.append(c)
    pd.concat([m, *clones]).to_parquet(m_path, index=False)

    legacy, columnar = _routes_panels(raw)
    _assert_panels_equal(legacy, columnar)


def _pipeline_artifacts(raw, route, monkeypatch):
    from fm_returnprediction_tpu import settings

    monkeypatch.setitem(settings.d, "PREPARED_CACHE", 0)
    monkeypatch.setenv("FMRP_PANEL_ROUTE", route)
    return run_pipeline(raw_data_dir=raw, make_figure=True,
                        make_deciles=True, compile_pdf=False)


def test_pipeline_artifacts_agree_across_routes(raw_dir, monkeypatch):
    """Table 1/2, decile table, figure cross-sections and the serving
    state are bit-identical between routes (the panels are, and every
    downstream stage is a deterministic function of the panel)."""
    res_l = _pipeline_artifacts(raw_dir, "legacy", monkeypatch)
    res_c = _pipeline_artifacts(raw_dir, "columnar", monkeypatch)

    # route evidence: legacy records load_raw_data, columnar streams
    assert "load_raw_data" in res_l.timer.durations
    assert "panel/monthly_ingest" in res_c.timer.durations
    assert "load_raw_data" not in res_c.timer.durations

    _assert_panels_equal(res_l.panel, res_c.panel)
    assert res_l.table_1.to_string() == res_c.table_1.to_string()
    assert res_l.table_2.to_string() == res_c.table_2.to_string()
    assert res_l.decile_table.to_string() == res_c.decile_table.to_string()

    s_l, s_c = res_l.serving_state, res_c.serving_state
    assert s_l is not None and s_c is not None
    np.testing.assert_array_equal(np.asarray(s_l.coef), np.asarray(s_c.coef))
    np.testing.assert_array_equal(
        np.asarray(s_l.slopes_bar), np.asarray(s_c.slopes_bar)
    )
    np.testing.assert_array_equal(np.asarray(s_l.gram), np.asarray(s_c.gram))
    np.testing.assert_array_equal(
        np.asarray(s_l.n_obs), np.asarray(s_c.n_obs)
    )

    # the figure sweep rides the same cross-sections both times
    from fm_returnprediction_tpu.reporting.figure1 import subset_sweep

    cs_l = subset_sweep(res_l.panel, res_l.subset_masks, ["All stocks"])
    cs_c = subset_sweep(res_c.panel, res_c.subset_masks, ["All stocks"])
    np.testing.assert_array_equal(
        np.asarray(cs_l["All stocks"].cs.slopes),
        np.asarray(cs_c["All stocks"].cs.slopes),
    )


def test_route_knob_validation(monkeypatch):
    monkeypatch.setenv("FMRP_PANEL_ROUTE", "columnar")
    assert panel_route() == "columnar"
    monkeypatch.setenv("FMRP_PANEL_ROUTE", "legacy")
    assert panel_route() == "legacy"
    monkeypatch.delenv("FMRP_PANEL_ROUTE")
    assert panel_route() == "columnar"  # the default route
    monkeypatch.setenv("FMRP_PANEL_ROUTE", "parquet-ish")
    with pytest.raises(ValueError, match="FMRP_PANEL_ROUTE"):
        panel_route()


def test_columnar_failure_falls_back_to_legacy(raw_dir, monkeypatch):
    """A cache the columnar reader cannot service degrades to the legacy
    route with a warning instead of failing the run."""
    from fm_returnprediction_tpu import settings
    from fm_returnprediction_tpu.data.columnar import ColumnarIngestError
    from fm_returnprediction_tpu.panel import columnar as pcol

    monkeypatch.setitem(settings.d, "PREPARED_CACHE", 0)

    def boom(*a, **k):
        raise ColumnarIngestError("synthetic unserviceable cache")

    monkeypatch.setattr(pcol, "build_dense_base_columnar", boom)
    with pytest.warns(UserWarning, match="falling back to the legacy"):
        panel, factors = load_or_build_panel(raw_dir, dtype=np.float64)
    assert "rolling_std_252" in panel.var_names


def test_missing_column_is_typed_ingest_error(tmp_path):
    """A monthly cache lacking a filter column raises the typed fallback
    signal, not a KeyError deep in numpy."""
    from fm_returnprediction_tpu.data.columnar import ColumnarIngestError

    raw = tmp_path / "nocol"
    write_synthetic_cache(raw, SyntheticConfig(n_firms=10, n_months=12))
    m_path = raw / FILE_NAMES["crsp_m"]
    m = pd.read_parquet(m_path).drop(columns=["sharetype"])
    m.to_parquet(m_path, index=False)
    with pytest.raises(ColumnarIngestError, match="sharetype"):
        build_panel_columnar(raw, dtype=np.float64)


def test_panel_program_no_retrace_on_warm_repeat(raw_dir):
    """The fused characteristics+winsorize program compiles once per
    shape/config — a warm repeat of the panel build must not re-trace."""
    from fm_returnprediction_tpu.panel import characteristics as ch

    build_panel_columnar(raw_dir, dtype=np.float64)
    before = ch.TRACES["panel_characteristics"]
    build_panel_columnar(raw_dir, dtype=np.float64)
    assert ch.TRACES["panel_characteristics"] == before


def test_prepared_v3_verify_and_corruption(raw_dir, tmp_path, monkeypatch):
    """v3 columnar checkpoint: mmap load passes full-hash verification;
    flipped payload bytes surface as a rebuild (miss + warning) when
    verification is armed."""
    from fm_returnprediction_tpu.data.prepared import (
        load_prepared,
        raw_fingerprint,
        save_prepared,
    )

    capture = {}
    build_panel(load_raw_data(raw_dir), capture=capture)
    fp = raw_fingerprint(raw_dir, np.float64)
    save_prepared(tmp_path, fp, capture["dense_base"],
                  capture["compact_daily"])

    monkeypatch.setenv("FMRP_PREPARED_VERIFY", "1")
    got = load_prepared(tmp_path, fp)
    assert got is not None
    base, cd = got
    # mmap'd payloads: zero-copy views over the files
    assert isinstance(base.values, np.memmap)
    assert isinstance(cd.row_values, np.memmap)
    np.testing.assert_array_equal(
        np.asarray(base.values), np.asarray(capture["dense_base"].values)
    )

    victim = tmp_path / "base.values.npy"
    raw_bytes = bytearray(victim.read_bytes())
    raw_bytes[-8] ^= 0xFF  # flip a payload byte, size unchanged
    victim.write_bytes(bytes(raw_bytes))
    with pytest.warns(UserWarning, match="sha256"):
        assert load_prepared(tmp_path, fp) is None

    # without verification the size check alone cannot see the bit-flip,
    # but a TRUNCATED payload is still caught structurally
    monkeypatch.setenv("FMRP_PREPARED_VERIFY", "0")
    victim.write_bytes(bytes(raw_bytes[:-16]))
    with pytest.warns(UserWarning, match="bytes"):
        assert load_prepared(tmp_path, fp) is None
