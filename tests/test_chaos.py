"""Chaos suite: every injected fault class must provably RECOVER.

Each test installs a deterministic :class:`FaultPlan` against a real
production code path and asserts the recovery the resilience layer
promises (ISSUE acceptance contract):

- transient IO error        → retried to success (WRDS pull loop);
- corrupt artifact          → typed checksum failure, resume path rebuilds;
- stalled runner            → the in-flight bucket FAILS, the microbatcher
                              keeps draining, later queries are unharmed;
- poisoned ingest month     → quarantined; the service keeps quoting from
                              the last-known-good state (degraded mode);
- mid-pipeline crash        → rerun resumes at the last completed stage.

Everything here is seeded/counter-gated — no wall-clock randomness — so a
failure replays exactly. Marked ``chaos`` (registered in pyproject); the
tests are fast and run in tier-1.
"""

import sys
import types

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.resilience import (
    CorruptArtifactError,
    DispatchTimeoutError,
    FaultPlan,
    FaultSpec,
)

pytestmark = pytest.mark.chaos


def _tiny_state(t=24, n=40, p=3, seed=11):
    from fm_returnprediction_tpu.serving import build_serving_state

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = (0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    return build_serving_state(y, x, mask, window=t // 2,
                               min_periods=t // 4), x


# -- transient IO error: retried to success --------------------------------

def test_transient_wrds_fault_retried_to_success(monkeypatch):
    """Two injected connection-layer faults cost two retries, not the
    pull: the third attempt lands and returns the frame."""
    from fm_returnprediction_tpu.data import wrds_pull

    class FakeConn:
        def __init__(self, wrds_username=""):
            pass

        def raw_sql(self, sql, date_cols=None):
            return pd.DataFrame({"x": [1]})

        def close(self):
            pass

    fake = types.ModuleType("wrds")
    fake.Connection = FakeConn
    monkeypatch.setitem(sys.modules, "wrds", fake)

    with FaultPlan({"wrds.query": FaultSpec(times=2)}) as plan:
        out = wrds_pull._wrds_query("SELECT 1", "u", [], retries=3,
                                    backoff_s=0.0)
    assert len(out) == 1
    assert plan.fired["wrds.query"] == 2 and plan.calls["wrds.query"] == 3

    # a persistent fault exhausts the budget with the typed error
    with FaultPlan({"wrds.query": FaultSpec(times=-1)}):
        with pytest.raises(RuntimeError, match="after 2 attempts"):
            wrds_pull._wrds_query("SELECT 1", "u", [], retries=1,
                                  backoff_s=0.0)


# -- corrupt artifact: typed failure, resume rebuilds ----------------------

def test_corrupted_serving_state_rebuilt_not_crashed(tmp_path):
    """An artifact corrupted after a (successful) write fails its checksum
    as a typed error, and the checkpoint resume path REBUILDS it instead
    of surfacing a cryptic numpy error."""
    from fm_returnprediction_tpu.resilience import StageCheckpointer
    from fm_returnprediction_tpu.serving.state import ServingState

    state, _ = _tiny_state()
    ck = StageCheckpointer(tmp_path, "fp")
    calls = {"n": 0}

    def compute():
        calls["n"] += 1
        return state

    kw = dict(saver=lambda st, path: st.save(path),
              loader=ServingState.load, suffix=".npz")
    ck.stage("serving_state", compute, **kw)
    assert calls["n"] == 1

    # corrupt the persisted npz the way a torn write / bit-rot would
    with FaultPlan({"cache.save_array_bundle": FaultSpec(corrupt=True)}):
        state.save(tmp_path / "serving_state.npz")  # overwrite + corrupt
    with pytest.raises(CorruptArtifactError):
        ServingState.load(tmp_path / "serving_state.npz")

    with pytest.warns(UserWarning, match="recomputing"):
        rebuilt = StageCheckpointer(tmp_path, "fp").stage(
            "serving_state", compute, **kw
        )
    assert calls["n"] == 2
    np.testing.assert_array_equal(rebuilt.slopes_bar, state.slopes_bar)


# -- stalled runner: bucket fails, batcher survives ------------------------

def test_stalled_dispatch_fails_bucket_without_hanging_batcher():
    """A runner stalled mid-dispatch is failed by the executor watchdog:
    the batch's futures get DispatchTimeoutError, the flusher thread keeps
    draining, and the NEXT query (fault healed) succeeds on the same
    service."""
    from fm_returnprediction_tpu.serving import ERService

    state, x = _tiny_state()
    t = state.n_months
    with ERService(state, max_batch=8, max_latency_ms=0.5, warm=True,
                   dispatch_timeout_s=0.25) as svc:
        row = x[t - 1, 0]
        with FaultPlan({"serving.dispatch": FaultSpec(times=1, delay_s=5.0)}):
            fut = svc.submit(t - 1, row)
            with pytest.raises(DispatchTimeoutError):
                fut.result(timeout=10.0)
        # the stall cost ONE bucket; the service is still live
        er = svc.query(t - 1, row, timeout=10.0)
        assert np.isfinite(er)
        stats = svc.stats()
        assert stats["dispatch_timeouts"] == 1
        assert stats["n_failed"] == 1 and stats["n_failed_batches"] == 1
        assert stats["n_done"] >= 1


# -- poisoned ingest month: quarantined, service stays quotable ------------

def test_poisoned_ingest_quarantined_service_stays_quotable():
    from fm_returnprediction_tpu.serving import ERService

    state, x = _tiny_state()
    t, n, p = state.n_months, x.shape[1], state.n_predictors
    month = np.datetime64("2071-03-31", "ns")
    with ERService(state, max_batch=8, warm=True) as svc:
        before = svc.query(t - 1, x[t - 1, 0])
        assert np.isfinite(before)

        # the poisoned feed: a NaN-flood cross-section injected at the
        # ingest fault site (what a broken upstream join produces)
        poison = FaultSpec(times=1, mutate=lambda payload: (
            np.full(n, np.nan),
            np.full((n, p), np.nan, np.float32),
            np.ones(n, bool),
        ))
        with FaultPlan({"serving.ingest": poison}):
            ok = svc.ingest_month(
                np.full(n, np.nan), x[t - 1, :, :], np.ones(n, bool), month
            )
        assert not ok and svc.degraded
        assert str(month) in svc.quarantined_months()
        assert "all-NaN" in svc.quarantined_months()[str(month)]
        assert svc.state.n_months == t          # last-known-good untouched

        # STILL QUOTABLE from the previous state, same answer
        after = svc.query(t - 1, x[t - 1, 0])
        assert after == pytest.approx(before)

        # a clean re-ingest of the same month heals the quarantine
        y_ok = np.full(n, np.nan, np.float32)   # start-of-month: no returns
        assert svc.ingest_month(y_ok, x[t - 1], np.ones(n, bool), month)
        assert not svc.degraded and svc.state.n_months == t + 1
        assert np.isfinite(svc.query(month, x[t - 1, 0]))
        stats = svc.stats()
        assert stats["n_ingest_failed"] == 1 and stats["n_ingested"] == 1


def test_shape_mismatch_and_merge_divergence_quarantined():
    from fm_returnprediction_tpu.serving import ERService

    state, x = _tiny_state()
    t, n = state.n_months, x.shape[1]
    with ERService(state, max_batch=8, warm=False, auto_flush=False,
                   merge_tolerance=1e-6) as svc:
        # wrong predictor width → rejected, not raised to the caller
        bad = np.zeros((n, state.n_predictors + 2), np.float32)
        assert not svc.ingest_month(np.zeros(n), bad, np.ones(n, bool),
                                    np.datetime64("2071-04-30", "ns"))
        assert svc.degraded and svc.stats()["n_ingest_failed"] == 1

        # merge re-ingest of the LAST month with wildly different rows →
        # divergence beyond tolerance → quarantined, state unchanged
        last = state.months[-1]
        rng = np.random.default_rng(0)
        y2 = rng.standard_normal(n).astype(np.float32) * 10
        x2 = rng.standard_normal((n, state.n_predictors)).astype(np.float32)
        old_coef = svc.state.coef.copy()
        assert not svc.ingest_month(y2, x2, np.ones(n, bool), last)
        np.testing.assert_array_equal(svc.state.coef, old_coef)
        assert str(np.datetime64(last, "ns")) in svc.quarantined_months()


# -- mid-pipeline crash: resume skips completed stages ---------------------

def test_pipeline_crash_resumes_at_last_completed_stage(tmp_path, monkeypatch):
    """Crash injected in the serving-state stage; the rerun loads Table 1
    and Table 2 from their stage checkpoints (builders not re-entered) and
    recomputes only the crashed stage."""
    import fm_returnprediction_tpu.pipeline as pl
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig

    kw = dict(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=20, n_months=36),
        make_figure=False, make_deciles=False, make_serving=True,
        compile_pdf=False, checkpoint_dir=tmp_path,
    )
    with FaultPlan({"pipeline.serving_state": FaultSpec(times=1)}):
        with pytest.raises(OSError, match="injected fault"):
            pl.run_pipeline(**kw)

    calls = {"table_1": 0, "table_2": 0}
    orig_t1, orig_t2 = pl.build_table_1, pl.build_table_2

    def count(name, orig):
        def inner(*a, **k):
            calls[name] += 1
            return orig(*a, **k)
        return inner

    monkeypatch.setattr(pl, "build_table_1", count("table_1", orig_t1))
    monkeypatch.setattr(pl, "build_table_2", count("table_2", orig_t2))
    res = pl.run_pipeline(**kw)
    assert calls == {"table_1": 0, "table_2": 0}   # resumed, not refit
    assert res.serving_state is not None
    assert res.table_1 is not None and res.table_2 is not None

    # and the resumed tables equal a from-scratch run's
    monkeypatch.undo()
    fresh = pl.run_pipeline(**{**kw, "checkpoint_dir": None})
    pd.testing.assert_frame_equal(res.table_1, fresh.table_1)
    pd.testing.assert_frame_equal(res.table_2, fresh.table_2)


# -- data-corruption sites: bad DATA, not exceptions ------------------------
#
# The second chaos class (guard-layer acceptance): each site injects a
# silently-wrong payload at a production fault site and must be caught at
# its DECLARED severity with a NAMED violation —
#
#   NaN flood            serving.ingest   quarantine  cs.nan_flood
#   duplicated permno    pipeline.panel   fail        panel.key_unique
#   stale repeated month serving.ingest   quarantine  cs.stale_repeat
#   permuted firm axis   pipeline.panel   warn        panel.ids_sorted
#   f32 scale spike      pipeline.panel   fail        panel.value_bounds
#
# (the NaN-flood site is already exercised by
# test_poisoned_ingest_quarantined_service_stays_quotable above)


def _pipeline_kw(**over):
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig

    kw = dict(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=20, n_months=36),
        make_figure=False, make_deciles=False, make_serving=False,
        compile_pdf=False, guard=True,
    )
    kw.update(over)
    return kw


def test_chaos_duplicated_permno_fails_panel_contract():
    from fm_returnprediction_tpu.pipeline import run_pipeline
    from fm_returnprediction_tpu.resilience.errors import (
        ContractViolationError,
    )
    from fm_returnprediction_tpu.resilience.faults import (
        corrupt_panel_duplicate_id,
    )

    plan = FaultPlan({
        "pipeline.panel": FaultSpec(mutate=corrupt_panel_duplicate_id)
    })
    with plan:
        with pytest.raises(ContractViolationError, match="panel.key_unique"):
            run_pipeline(**_pipeline_kw())
    assert plan.fired["pipeline.panel"] == 1


def test_chaos_stale_month_fails_calendar_contract():
    from fm_returnprediction_tpu.pipeline import run_pipeline
    from fm_returnprediction_tpu.resilience.errors import (
        ContractViolationError,
    )
    from fm_returnprediction_tpu.resilience.faults import (
        corrupt_panel_stale_month,
    )

    with FaultPlan({
        "pipeline.panel": FaultSpec(mutate=corrupt_panel_stale_month)
    }):
        with pytest.raises(
            ContractViolationError, match="panel.calendar_monotone"
        ):
            run_pipeline(**_pipeline_kw())


def test_chaos_scale_spike_fails_value_bounds():
    from fm_returnprediction_tpu.pipeline import run_pipeline
    from fm_returnprediction_tpu.resilience.errors import (
        ContractViolationError,
    )
    from fm_returnprediction_tpu.resilience.faults import (
        corrupt_panel_scale_spike,
    )

    with FaultPlan({
        "pipeline.panel": FaultSpec(
            mutate=lambda p: corrupt_panel_scale_spike(p, column=-1)
        )
    }):
        with pytest.raises(ContractViolationError, match="panel.value_bounds"):
            run_pipeline(**_pipeline_kw())


def test_chaos_permuted_firm_axis_warns_and_run_completes():
    """A coherent firm-axis permutation changes NO statistic — the run
    must COMPLETE (warn severity), emit the named violation into the audit
    record, and produce the same Table 2 as the unpermuted run."""
    from fm_returnprediction_tpu.guard.contracts import GuardWarning
    from fm_returnprediction_tpu.pipeline import run_pipeline
    from fm_returnprediction_tpu.resilience.faults import (
        corrupt_panel_permute_firms,
    )

    clean = run_pipeline(**_pipeline_kw())
    with FaultPlan({
        "pipeline.panel": FaultSpec(
            mutate=lambda p: corrupt_panel_permute_firms(p, seed=4)
        )
    }):
        with pytest.warns(GuardWarning, match="panel.ids_sorted"):
            res = run_pipeline(**_pipeline_kw())
    assert "panel.ids_sorted" in res.audit.names()
    pd.testing.assert_frame_equal(res.table_2, clean.table_2)


def test_chaos_stale_repeated_month_quarantined_at_serving():
    """The upstream feed re-offers the state's last cross-section under a
    NEW month label: quarantined as cs.stale_repeat, service keeps
    quoting, and a genuinely fresh month afterwards heals it."""
    from fm_returnprediction_tpu.serving import ERService, build_serving_state

    rng = np.random.default_rng(17)
    t, n, p = 24, 40, 3
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = np.where(
        rng.random((t, n)) > 0.2, 0.1 * rng.standard_normal((t, n)), np.nan
    ).astype(np.float32)
    mask = np.ones((t, n), bool)  # full mask: the support bounds the state
    # stores for month t-1 are exactly what a re-offered (x, mask) yields
    state = build_serving_state(y, x, mask, window=t // 2,
                                min_periods=t // 4)
    last_x = x[t - 1]
    stale_month = np.datetime64("2071-05-31", "ns")
    with ERService(state, max_batch=8, warm=True) as svc:
        before = svc.query(t - 1, last_x[0])

        # the chaos plan swaps the fresh feed for yesterday's data
        stale = FaultSpec(times=1, mutate=lambda payload: (
            np.full(n, np.nan, np.float32), last_x, np.ones(n, bool),
        ))
        fresh_x = last_x + np.float32(0.125)
        with FaultPlan({"serving.ingest": stale}) as plan:
            ok = svc.ingest_month(
                np.full(n, np.nan), fresh_x, np.ones(n, bool), stale_month
            )
        assert plan.fired["serving.ingest"] == 1
        assert not ok and svc.degraded
        assert "cs.stale_repeat" in svc.quarantined_months()[str(stale_month)]
        assert "cs.stale_repeat" in svc.audit.names()  # named in the ledger
        assert svc.state.n_months == t

        # still quotable from last-known-good, same answer
        assert svc.query(t - 1, last_x[0]) == pytest.approx(before)

        # the healed feed (no plan) ingests the genuinely fresh month
        assert svc.ingest_month(
            np.full(n, np.nan), fresh_x, np.ones(n, bool), stale_month
        )
        assert not svc.degraded and svc.state.n_months == t + 1


def test_chaos_nan_flood_names_violation_in_audit():
    """The pre-existing NaN-flood site, now routed through the shared
    contract rules: the quarantine reason carries the rule name."""
    from fm_returnprediction_tpu.resilience.faults import poison_nan_flood
    from fm_returnprediction_tpu.serving import ERService

    state, x = _tiny_state()
    t, n = state.n_months, x.shape[1]
    with ERService(state, max_batch=8, warm=False, auto_flush=False) as svc:
        with FaultPlan({
            "serving.ingest": FaultSpec(times=1, mutate=poison_nan_flood)
        }):
            ok = svc.ingest_month(
                np.full(n, np.nan), x[t - 1], np.ones(n, bool),
                np.datetime64("2071-06-30", "ns"),
            )
        assert not ok
        (reason,) = svc.quarantined_months().values()
        assert "cs.nan_flood" in reason and "all-NaN" in reason
        assert "cs.nan_flood" in svc.audit.names()
