"""Registry Gram bank: banked month-axis stats answering window /
bootstrap / scenario queries with zero panel reads (``specgrid.grambank``).

The ISSUE-14 part-(c) contracts:

- a window query over banked stats matches the full grid route (the
  refereed engine) at f64 ≤ 1e-12 with exactly equal month counts;
- a bootstrap query rides the device-batched aggregator on the SAME
  archived draw seeds as the tile engine, pinned against the host oracle;
- ``ingest_month`` extends every leaf by Gram additivity — the appended
  bank matches a from-scratch contraction of the longer panel;
- the registry roundtrip: content-addressed save/load, env-skew (x64)
  reads as a warned miss, corruption degrades to a warned miss, no
  registry means no banking (never an error);
- the scenarios path: ``run_scenarios_banked`` reproduces
  ``run_scenarios``'s numbers per (model, universe, window, predictor)
  without touching the ``(T, N, P)`` panel (the contraction ledger stays
  flat across queries).
"""

import numpy as np
import pytest

from fm_returnprediction_tpu.specgrid.boot import fm_aggregate_np
from fm_returnprediction_tpu.specgrid.cellspace import CellSpace
from fm_returnprediction_tpu.specgrid.grambank import (
    bank_key,
    bootstrap_query,
    build_bank,
    ingest_month,
    load_bank,
    save_bank,
    scenario_query,
    window_query,
)
from fm_returnprediction_tpu.specgrid.solve import (
    contraction_counts,
    run_spec_grid,
)
from fm_returnprediction_tpu.specgrid.specs import Spec, SpecGrid

pytestmark = [pytest.mark.specgrid, pytest.mark.registry]


def _panel(seed=0, t=30, n=140, p=4):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p))
    x[rng.random(x.shape) < 0.06] = np.nan
    beta = rng.standard_normal(p) * 0.1
    y = np.nansum(x * beta, axis=-1) + 0.3 * rng.standard_normal((t, n))
    y[rng.random(y.shape) < 0.1] = np.nan
    masks = {
        "All": np.ones((t, n), bool),
        "Big": (rng.random(n) > 0.35)[None, :] & np.ones((t, n), bool),
    }
    return y, x, masks


def _space(t, p=4, **kw):
    names = tuple(f"c{i}" for i in range(p))
    defaults = dict(
        regressor_sets=(("m2", names[:2]), ("mfull", names)),
        universes=("All", "Big"),
        windows=(("full", None), ("half1", (0, t // 2)),
                 ("half2", (t // 2, t))),
        nw_lags=4, min_months=8,
    )
    defaults.update(kw)
    return CellSpace(**defaults)


@pytest.fixture()
def bank():
    y, x, masks = _panel()
    space = _space(y.shape[0])
    return build_bank(y, x, masks, space, fingerprint="test-bank"), \
        (y, x, masks, space)


# -- window queries ----------------------------------------------------------

def test_window_query_matches_grid_route(bank):
    bk, (y, x, masks, space) = bank
    assert bk.n_pairs == 4  # 2 sets × 2 universes
    names = tuple(space.union_predictors)
    for window, win_arg in ((None, None), ((5, 25), (5, 25))):
        specs = tuple(
            Spec(f"{s}_{u}", cols, u, window=window)
            for s, cols in space.regressor_sets for u in space.universes
        )
        grid = SpecGrid(specs, nw_lags=space.nw_lags,
                        min_months=space.min_months, union=names)
        ref = run_spec_grid(y, x, masks, grid)
        got = window_query(bk, win_arg)
        np.testing.assert_array_equal(got.n_months, ref.n_months)
        np.testing.assert_array_equal(got.month_valid, ref.month_valid)
        for f in ("coef", "nw_se", "mean_r2", "mean_n", "slopes", "r2"):
            a = np.asarray(getattr(ref, f), float)
            b = np.asarray(getattr(got, f), float)
            np.testing.assert_array_equal(np.isnan(a), np.isnan(b),
                                          err_msg=f)
            np.testing.assert_allclose(b, a, atol=1e-12, equal_nan=True,
                                       err_msg=f)
        np.testing.assert_allclose(got.tstat, ref.tstat, atol=1e-10,
                                   equal_nan=True)


def test_window_query_mask_and_bounds(bank):
    bk, _ = bank
    t = bk.n_months
    mask = np.zeros(t, bool)
    mask[::2] = True
    got = window_query(bk, mask)
    assert (got.n_months <= mask.sum()).all()
    with pytest.raises(ValueError, match="window mask"):
        window_query(bk, np.ones(t + 1, bool))
    # a (lo, hi) range matching NO banked labels fails loudly — the
    # label/position confusion a calendar-labelled bank invites
    with pytest.raises(ValueError, match="month LABELS"):
        window_query(bk, (10 * t, 20 * t))


# -- bootstrap queries -------------------------------------------------------

def test_bootstrap_query_matches_host_oracle(bank):
    bk, (y, x, masks, space) = bank
    from fm_returnprediction_tpu.specgrid.boot import resample_matrix

    draws, seed = 8, 3
    point, stacks = bootstrap_query(bk, draws, window=None, seed=seed)
    idx = resample_matrix(bk.n_months, draws, seed=seed)
    assert len(stacks) == bk.n_pairs
    for k in range(bk.n_pairs):
        coef_d, tstat_d, nw_d, r2_d, n_d, m_d = stacks[k]
        assert coef_d.shape == (draws - 1, len(bk.union))
        for d in range(draws - 1):
            rows = idx[d]
            ref = fm_aggregate_np(
                point.slopes[k][rows], point.r2[k][rows],
                point.n_obs[k][rows], point.month_valid[k][rows],
                space.nw_lags, space.min_months, "reference",
            )
            np.testing.assert_allclose(coef_d[d], ref[0], atol=1e-12,
                                       equal_nan=True)
            np.testing.assert_allclose(nw_d[d], ref[2], atol=1e-12,
                                       equal_nan=True)
            assert int(m_d[d]) == ref[5]
    with pytest.raises(ValueError, match="draws"):
        bootstrap_query(bk, 0)


# -- ingest ------------------------------------------------------------------

def test_ingest_month_additivity(bank):
    bk_full, (y, x, masks, space) = bank
    t = y.shape[0]
    head = build_bank(y[: t - 1], x[: t - 1],
                      {k: v[: t - 1] for k, v in masks.items()},
                      _space(t, p=x.shape[2]), fingerprint="test-bank")
    grown = ingest_month(
        head, y[t - 1], x[t - 1],
        {k: v[t - 1] for k, v in masks.items()}, month=t - 1,
    )
    assert grown.n_months == t
    for f in ("gram", "moment", "n", "ysum", "yy", "center"):
        a = np.asarray(getattr(bk_full, f))
        np.testing.assert_allclose(np.asarray(getattr(grown, f)), a,
                                   atol=1e-13 * max(np.nanmax(np.abs(a)), 1),
                                   err_msg=f)
    np.testing.assert_array_equal(grown.months, bk_full.months)
    # and the grown bank answers queries like the from-scratch one
    np.testing.assert_allclose(
        window_query(grown).coef, window_query(bk_full).coef,
        atol=1e-11, equal_nan=True,
    )
    with pytest.raises(ValueError, match="already banked"):
        ingest_month(grown, y[t - 1], x[t - 1],
                     {k: v[t - 1] for k, v in masks.items()}, month=t - 1)
    with pytest.raises(ValueError, match="union"):
        ingest_month(grown, y[t - 1], x[t - 1][:, :2],
                     {k: v[t - 1] for k, v in masks.items()}, month=t)


# -- registry roundtrip ------------------------------------------------------

def test_save_load_roundtrip(bank, tmp_path, monkeypatch):
    bk, _ = bank
    monkeypatch.setenv("FMRP_REGISTRY_DIR", str(tmp_path / "reg"))
    entry = save_bank(bk)
    assert entry is not None and (entry / "bank.npz").exists()
    got = load_bank("test-bank", bk.union, bk.universes, bk.uidx,
                    bk.col_sel, bk.dtype, bk.months)
    assert got is not None
    for f in ("gram", "moment", "n", "ysum", "yy", "center", "months",
              "uidx", "col_sel"):
        np.testing.assert_array_equal(getattr(got, f), getattr(bk, f),
                                      err_msg=f)
    assert got.union == bk.union and got.pair_labels == bk.pair_labels
    # a different fingerprint is a different address: miss
    assert load_bank("other", bk.union, bk.universes, bk.uidx,
                     bk.col_sel, bk.dtype, bk.months) is None
    # a grown month axis is a different address too — an ingest-grown
    # bank can never silently REPLACE its parent entry
    assert load_bank("test-bank", bk.union, bk.universes, bk.uidx,
                     bk.col_sel, bk.dtype,
                     np.arange(bk.n_months + 1)) is None


def test_registry_off_means_no_banking(bank, monkeypatch):
    bk, _ = bank
    monkeypatch.delenv("FMRP_REGISTRY_DIR", raising=False)
    assert save_bank(bk) is None
    assert load_bank("test-bank", bk.union, bk.universes, bk.uidx,
                     bk.col_sel, bk.dtype, bk.months) is None


def test_load_miss_on_env_skew_and_corruption(bank, tmp_path, monkeypatch):
    import json

    bk, _ = bank
    monkeypatch.setenv("FMRP_REGISTRY_DIR", str(tmp_path / "reg"))
    entry = save_bank(bk)
    meta_path = entry / "meta.json"
    meta = json.loads(meta_path.read_text())
    meta["x64"] = not meta["x64"]
    meta_path.write_text(json.dumps(meta))
    with pytest.warns(UserWarning, match="skewed"):
        assert load_bank("test-bank", bk.union, bk.universes, bk.uidx,
                         bk.col_sel, bk.dtype, bk.months) is None
    meta["x64"] = not meta["x64"]
    meta_path.write_text(json.dumps(meta))
    # corrupt the payload: the manifest check trips and degrades to a miss
    (entry / "bank.npz").write_bytes(b"not an npz")
    with pytest.warns(UserWarning, match="unreadable|re-contracting"):
        assert load_bank("test-bank", bk.union, bk.universes, bk.uidx,
                         bk.col_sel, bk.dtype, bk.months) is None


def test_bank_key_sensitivity(bank):
    bk, _ = bank
    m = bk.months
    base = bank_key("fp", bk.union, bk.universes, bk.uidx, bk.col_sel,
                    "float64", m, "xla", "highest")
    assert base == bank_key("fp", bk.union, bk.universes, bk.uidx,
                            bk.col_sel, "float64", m, "xla", "highest")
    others = [
        bank_key("fp2", bk.union, bk.universes, bk.uidx, bk.col_sel,
                 "float64", m, "xla", "highest"),
        bank_key("fp", bk.union, bk.universes, bk.uidx, bk.col_sel,
                 "float32", m, "xla", "highest"),
        bank_key("fp", bk.union, bk.universes, bk.uidx, bk.col_sel,
                 "float64", m, "pallas", "highest"),
        bank_key("fp", bk.union, bk.universes, bk.uidx, bk.col_sel,
                 "float64", m, "xla", "bf16"),
        bank_key("fp", bk.union, bk.universes, bk.uidx[::-1].copy(),
                 bk.col_sel, "float64", m, "xla", "highest"),
        bank_key("fp", bk.union, bk.universes, bk.uidx, bk.col_sel,
                 "float64", np.concatenate([m, [m[-1] + 1]]), "xla",
                 "highest"),
    ]
    assert len({base, *others}) == len(others) + 1


# -- the scenarios path ------------------------------------------------------

def test_scenario_query_schema_and_zero_panel_reads(bank):
    bk, _ = bank
    before = contraction_counts()
    frame = scenario_query(
        bk, windows={"full": None, "late": (20, 30)}, bootstrap=3,
        label_of={"c0": "Beta"},
    )
    after = contraction_counts()
    # zero panel reads: the contraction-work ledger did not move
    assert before == after
    expected = {"model", "universe", "window", "nw_weight", "predictor",
                "coef", "tstat", "nw_se", "mean_r2", "mean_n", "n_months",
                "refereed", "suspect_months", "source", "draw"}
    assert expected <= set(frame.columns)
    assert (frame["source"] == "bank").all()
    assert (~frame["refereed"]).all()
    assert set(frame["window"]) == {"full", "late"}
    assert set(frame["draw"]) == {0, 1, 2}
    assert "Beta" in set(frame["predictor"])
    # rows: windows × pairs × draws × selected predictors
    n_sel = int(bk.col_sel.sum())
    assert len(frame) == 2 * 3 * n_sel


def test_run_scenarios_banked_matches_run_scenarios():
    from fm_returnprediction_tpu.models.lewellen import ModelSpec
    from fm_returnprediction_tpu.specgrid.scenarios import (
        bank_for_scenarios,
        run_scenarios,
        run_scenarios_banked,
        subperiod_windows,
    )

    y, x, masks = _panel(seed=21, t=36, n=80, p=3)
    names = ["c0", "c1", "c2"]

    class _MiniPanel:
        def __init__(self):
            self.mask = masks["All"]
            self.months = np.arange(y.shape[0])

        def var(self, name):
            assert name == "retx"
            return y

        def select(self, cols):
            return x[:, :, [names.index(c) for c in cols]]

    panel = _MiniPanel()
    variables = {"V0": "c0", "V1": "c1", "V2": "c2"}
    models = [ModelSpec("Model A", ["V0", "V1"]),
              ModelSpec("Model B", ["V0", "V1", "V2"])]
    ref = run_scenarios(panel, masks, variables, models=models,
                        subperiods=2, min_months=8)
    bk = bank_for_scenarios(panel, masks, variables, models=models,
                            min_months=8)
    got = run_scenarios_banked(
        bk, windows=subperiod_windows(bk.n_months, 2),
        variables_dict=variables,
    )
    keys = ["model", "universe", "window", "predictor"]
    merged = ref.merge(got, on=keys, suffixes=("_ref", "_bank"))
    assert len(merged) == len(ref) == len(got)
    for f in ("coef", "tstat", "nw_se", "mean_r2", "mean_n"):
        np.testing.assert_allclose(
            merged[f"{f}_bank"], merged[f"{f}_ref"], atol=1e-9,
            equal_nan=True, err_msg=f,
        )
    assert (merged["n_months_bank"] == merged["n_months_ref"]).all()
