"""Firm-axis chunking must be a pure execution-schedule choice: identical
results to the single-call daily kernels for every chunk width, including
non-divisible widths (padded last strip) and the auto heuristic."""

import jax.numpy as jnp
import numpy as np
import pytest

from fm_returnprediction_tpu.ops.daily_chunked import (
    auto_firm_chunk,
    daily_characteristics_chunked,
)
from fm_returnprediction_tpu.ops.daily_kernels import (
    rolling_vol_252_monthly,
    weekly_rolling_beta_monthly,
)


def _daily_fixture(rng, d=240, n=37, n_months=12):
    ret = rng.standard_normal((d, n)) * 0.02
    mask = rng.random((d, n)) > 0.25
    ret = np.where(rng.random((d, n)) > 0.05, ret, np.nan)  # nulls inside rows
    mkt = rng.standard_normal(d) * 0.01
    mkt_present = rng.random(d) > 0.03
    mkt = np.where(mkt_present, mkt, np.nan)
    month_id = np.repeat(np.arange(n_months), d // n_months).astype(np.int32)
    week_id = (np.arange(d) // 5).astype(np.int32)
    n_weeks = int(week_id.max()) + 1
    week_month_id = np.clip(np.arange(n_weeks) // 4, 0, n_months - 1).astype(np.int32)
    return dict(
        ret_d=ret, mask_d=mask, mkt_d=mkt, month_id=month_id,
        week_id=week_id, week_month_id=week_month_id,
        n_months=n_months, n_weeks=n_weeks, mkt_present=mkt_present,
    )


def _unchunked(d, window=60, min_periods=20, window_weeks=26):
    vol = rolling_vol_252_monthly(
        jnp.asarray(d["ret_d"]), jnp.asarray(d["mask_d"]),
        jnp.asarray(d["month_id"]), d["n_months"],
        window=window, min_periods=min_periods,
    )
    beta = weekly_rolling_beta_monthly(
        jnp.asarray(d["ret_d"]), jnp.asarray(d["mask_d"]),
        jnp.asarray(d["mkt_d"]), jnp.asarray(d["week_id"]), d["n_weeks"],
        jnp.asarray(d["week_month_id"]), d["n_months"],
        window_weeks=window_weeks, mkt_present=jnp.asarray(d["mkt_present"]),
    )
    return np.asarray(vol), np.asarray(beta)


@pytest.mark.parametrize("chunk", [1, 7, 16, 37, 64])
def test_chunked_matches_single_call(rng, chunk):
    d = _daily_fixture(rng)
    vol0, beta0 = _unchunked(d)
    vol, beta = daily_characteristics_chunked(
        **d, window=60, min_periods=20, window_weeks=26, firm_chunk=chunk
    )
    np.testing.assert_array_equal(vol, vol0)
    np.testing.assert_array_equal(beta, beta0)


def test_auto_chunk_heuristic():
    # whole panel fits → no chunking
    assert auto_firm_chunk(240, 37, 8, budget_bytes=1 << 30) is None
    # full CRSP scale in f32 on a 16 GiB chip → a few-thousand-firm strip
    c = auto_firm_chunk(12608, 25000, 4, budget_bytes=int(9.6e9))
    assert c is not None and 128 <= c < 25000 and c % 128 == 0
    # tiny budget still returns the floor width, never 0
    assert auto_firm_chunk(12608, 25000, 8, budget_bytes=1) == 128


def _to_csr(d):
    """Dense fixture → compacted CSR layout (firm-major chronological rows)."""
    mask = d["mask_d"]
    n_days, n_firms = mask.shape
    row_values, row_pos, offsets = [], [], [0]
    for f in range(n_firms):
        rows = np.nonzero(mask[:, f])[0]
        row_values.append(d["ret_d"][rows, f])
        row_pos.append(rows)
        offsets.append(offsets[-1] + len(rows))
    return dict(
        row_values=np.concatenate(row_values),
        row_pos=np.concatenate(row_pos).astype(np.int16),
        offsets=np.asarray(offsets, dtype=np.int64),
        mkt_d=d["mkt_d"],
        mkt_present=d["mkt_present"],
        day_month_id=d["month_id"],
        week_id=d["week_id"],
        week_month_id=d["week_month_id"],
        n_days=n_days,
        n_weeks=d["n_weeks"],
        n_months=d["n_months"],
    )


@pytest.mark.parametrize("chunk,bucket", [(37, 64), (10, 32), (8, 256)])
def test_compact_chunked_matches_dense(rng, chunk, bucket):
    """The compacted-ingest path matches the dense kernels for every strip
    width and height bucket — including firms reordered by row count and the
    padded final strip."""
    from fm_returnprediction_tpu.ops.daily_chunked import (
        daily_characteristics_compact_chunked,
    )

    d = _daily_fixture(rng)
    vol0, beta0 = _unchunked(d)
    csr = _to_csr(d)
    vol, beta = daily_characteristics_compact_chunked(
        **csr, window=60, min_periods=20, window_weeks=26,
        firm_chunk=chunk, height_bucket=bucket,
    )
    # bit-exact: the strip kernel reconstructs the dense grid on device and
    # runs the SAME dense kernels, so chunking + compact ingest is purely an
    # execution-schedule choice
    np.testing.assert_array_equal(vol, vol0)
    np.testing.assert_array_equal(beta, beta0)


def _contiguous_daily_fixture(rng, d=240, n=31, n_months=12):
    """Every firm's rows span a contiguous day range (CRSP-like: rows exist
    for each trading day while listed; nulls are NaN VALUES on present
    rows) — the regime the starts/counts ingest variant targets."""
    base = _daily_fixture(rng, d=d, n=n, n_months=n_months)
    mask = np.zeros((d, n), dtype=bool)
    for k in range(n):
        a = int(rng.integers(0, d - 20))
        b = int(rng.integers(a + 10, d))
        mask[a:b, k] = True
    base["mask_d"] = mask
    return base


def test_compact_strip_contiguous_matches_pos_path(rng):
    """The starts/counts variant is byte-for-byte the pos-rectangle strip
    program on contiguous data."""
    from fm_returnprediction_tpu.ops.daily_compact import (
        daily_compact_strip,
        daily_compact_strip_contiguous,
    )

    d = _contiguous_daily_fixture(rng)
    csr = _to_csr(d)
    counts = np.diff(csr["offsets"])
    n_firms = len(counts)
    h = int(counts.max())
    rect_vals = np.full((h, n_firms), np.nan)
    rect_pos = np.full((h, n_firms), csr["n_days"], dtype=csr["row_pos"].dtype)
    starts = np.zeros(n_firms, np.int32)
    for k in range(n_firms):
        a, b = csr["offsets"][k], csr["offsets"][k + 1]
        rect_vals[: b - a, k] = csr["row_values"][a:b]
        rect_pos[: b - a, k] = csr["row_pos"][a:b]
        starts[k] = csr["row_pos"][a]
    shared = (
        jnp.asarray(csr["mkt_d"]), jnp.asarray(csr["mkt_present"]),
        jnp.asarray(csr["day_month_id"]), jnp.asarray(csr["week_id"]),
        jnp.asarray(csr["week_month_id"]),
    )
    kw = dict(n_days=csr["n_days"], n_weeks=csr["n_weeks"],
              n_months=csr["n_months"], window=60, min_periods=20,
              window_weeks=26, use_pallas=False)
    vol_p, beta_p = daily_compact_strip(
        jnp.asarray(rect_vals), jnp.asarray(rect_pos), *shared, **kw
    )
    vol_c, beta_c = daily_compact_strip_contiguous(
        jnp.asarray(rect_vals), jnp.asarray(starts),
        jnp.asarray(counts.astype(np.int32)), *shared, **kw
    )
    np.testing.assert_array_equal(np.asarray(vol_c), np.asarray(vol_p))
    np.testing.assert_array_equal(np.asarray(beta_c), np.asarray(beta_p))


@pytest.mark.parametrize("use_mesh", [False, True])
@pytest.mark.parametrize("chunk", [8, 40])
def test_compact_chunked_contiguous_matches_dense(rng, chunk, use_mesh):
    """End-to-end: the chunked driver auto-selects the starts/counts ingest
    on contiguous data (single-device and mesh) and still reproduces the
    dense kernels bit-exactly."""
    from fm_returnprediction_tpu.ops.daily_chunked import (
        daily_characteristics_compact_chunked,
    )
    from fm_returnprediction_tpu.parallel.mesh import make_mesh

    d = _contiguous_daily_fixture(rng)
    vol0, beta0 = _unchunked(d)
    csr = _to_csr(d)
    mesh = make_mesh(axis_name="firms") if use_mesh else None
    vol, beta = daily_characteristics_compact_chunked(
        **csr, window=60, min_periods=20, window_weeks=26,
        firm_chunk=chunk, mesh=mesh, use_pallas=False if mesh is None else None,
    )
    np.testing.assert_array_equal(vol, vol0)
    np.testing.assert_array_equal(beta, beta0)


def test_compact_chunked_empty_firms(rng):
    """Zero-row firms in the CSR (valid public-API input) must produce
    all-NaN columns, not crash the contiguity precompute — including an
    empty firm at position 0 and at the end."""
    from fm_returnprediction_tpu.ops.daily_chunked import (
        daily_characteristics_compact_chunked,
    )

    d = _contiguous_daily_fixture(rng, n=9)
    csr = _to_csr(d)
    # splice empty firms at the front and back of the firm axis
    offsets = np.concatenate([[0], csr["offsets"], [csr["offsets"][-1]]])
    csr = {**csr, "offsets": offsets}
    vol, beta = daily_characteristics_compact_chunked(
        **csr, window=60, min_periods=20, window_weeks=26, firm_chunk=4,
        use_pallas=False,
    )
    assert vol.shape[1] == 11
    assert np.isnan(vol[:, 0]).all() and np.isnan(vol[:, -1]).all()
    assert np.isnan(beta[:, 0]).all() and np.isnan(beta[:, -1]).all()
    assert np.isfinite(vol[:, 1:-1]).any()


@pytest.mark.parametrize("chunk", [16, 40])
def test_compact_chunked_mesh_matches_single_device(rng, chunk):
    """The mesh path consumes the SAME compact ingest (round-2 VERDICT
    item 5): sharding each strip's firm axis over the 8-device mesh is a
    pure execution-schedule choice — outputs are bit-identical to the
    single-device compact path, and the strip program stays collective-free
    under SPMD partitioning."""
    import jax

    from fm_returnprediction_tpu.ops.daily_chunked import (
        daily_characteristics_compact_chunked,
    )
    from fm_returnprediction_tpu.ops.daily_compact import daily_compact_strip
    from fm_returnprediction_tpu.parallel.mesh import make_mesh

    d = _daily_fixture(rng)
    csr = _to_csr(d)
    kw = dict(window=60, min_periods=20, window_weeks=26)
    vol0, beta0 = daily_characteristics_compact_chunked(
        **csr, **kw, firm_chunk=chunk, use_pallas=False
    )
    mesh = make_mesh(axis_name="firms")
    vol, beta = daily_characteristics_compact_chunked(
        **csr, **kw, firm_chunk=chunk, mesh=mesh
    )
    np.testing.assert_array_equal(vol, vol0)
    np.testing.assert_array_equal(beta, beta0)

    # the shard_map'd strip program must contain no collectives
    from jax.sharding import NamedSharding, PartitionSpec as P

    import jax.numpy as jnp

    from fm_returnprediction_tpu.ops.daily_chunked import _mesh_strip_fn

    h, c = 64, 16
    rect_vals = jax.device_put(
        jnp.zeros((h, c)), NamedSharding(mesh, P(None, "firms"))
    )
    rect_pos = jax.device_put(
        jnp.full((h, c), csr["n_days"], dtype=np.int32),
        NamedSharding(mesh, P(None, "firms")),
    )
    rep = NamedSharding(mesh, P())
    mesh_fn = _mesh_strip_fn(
        mesh, "firms", csr["n_days"], csr["n_weeks"], csr["n_months"],
        kw["window"], kw["min_periods"], kw["window_weeks"],
    )
    hlo = mesh_fn.lower(
        rect_vals, rect_pos,
        jax.device_put(jnp.asarray(csr["mkt_d"]), rep),
        jax.device_put(jnp.asarray(csr["mkt_present"]), rep),
        jax.device_put(jnp.asarray(csr["day_month_id"]), rep),
        jax.device_put(jnp.asarray(csr["week_id"]), rep),
        jax.device_put(jnp.asarray(csr["week_month_id"]), rep),
    ).compile().as_text()
    for op in ("all-reduce", "all-gather", "collective-permute", "all-to-all",
               "reduce-scatter"):
        assert op not in hlo, f"unexpected collective {op} in compact strip program"


def test_build_compact_daily_matches_dense_panel(rng):
    """Host CSR builder agrees with the dense builder on the synthetic
    universe: same ids/day vocabulary, and rows land at the same positions."""
    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.panel.daily import (
        build_compact_daily,
        build_daily_panel,
    )

    data = generate_synthetic_wrds(SyntheticConfig(n_firms=25, n_months=30))
    months = np.sort(data["crsp_m"]["jdate"].unique())
    dense = build_daily_panel(data["crsp_d"], data["crsp_index_d"], months)
    cd = build_compact_daily(data["crsp_d"], data["crsp_index_d"], months)

    np.testing.assert_array_equal(cd.ids, dense.ids)
    np.testing.assert_array_equal(cd.days, dense.days)
    np.testing.assert_array_equal(cd.day_month_id, dense.day_month_id)
    np.testing.assert_array_equal(cd.week_id, dense.week_id)
    np.testing.assert_array_equal(cd.week_month_id, dense.week_month_id)
    assert cd.n_weeks == dense.n_weeks and cd.n_months == dense.n_months
    # CSR rows reproduce the dense grid exactly
    rebuilt = np.full_like(dense.ret, np.nan)
    mask = np.zeros_like(dense.mask)
    for f in range(len(cd.ids)):
        a, b = cd.offsets[f], cd.offsets[f + 1]
        rebuilt[cd.row_pos[a:b].astype(np.int64), f] = cd.row_values[a:b]
        mask[cd.row_pos[a:b].astype(np.int64), f] = True
    np.testing.assert_array_equal(mask, dense.mask)
    np.testing.assert_array_equal(
        np.where(mask, rebuilt, np.nan), np.where(dense.mask, dense.ret, np.nan)
    )


def test_compact_builder_dedups_keep_last(rng):
    """Duplicate (permno, day) rows must dedup keep-last, matching
    long_to_dense, so the compact and dense/mesh paths agree."""
    import pandas as pd

    from fm_returnprediction_tpu.panel.daily import build_compact_daily

    crsp_d = pd.DataFrame(
        {
            "permno": [1, 1, 1, 2],
            "dlycaldt": pd.to_datetime(
                ["2000-01-03", "2000-01-03", "2000-01-04", "2000-01-03"]
            ),
            "retx": [0.10, 0.20, 0.30, 0.40],
        }
    )
    idx = pd.DataFrame(
        {"caldt": pd.to_datetime(["2000-01-03", "2000-01-04"]), "vwretx": [0.0, 0.0]}
    )
    months = np.asarray(pd.to_datetime(["2000-01-31"]))
    cd = build_compact_daily(crsp_d, idx, months)
    assert list(cd.counts) == [2, 1]
    a, b = cd.offsets[0], cd.offsets[1]
    assert cd.row_values[a] == 0.20  # keep-last won


def test_compact_builder_day_vocab_misaligned_timestamps(rng):
    """Intraday (non-midnight) timestamps take the hash-factorize fallback
    and stay DISTINCT vocabulary entries — the direct-address day table must
    not silently bucket them into calendar days."""
    import pandas as pd

    from fm_returnprediction_tpu.panel.daily import build_compact_daily

    ts = pd.to_datetime(
        ["2000-01-03 00:00", "2000-01-03 10:30", "2000-01-04 00:00"]
    )
    crsp_d = pd.DataFrame(
        {"permno": [1, 1, 1], "dlycaldt": ts, "retx": [0.1, 0.2, 0.3]}
    )
    idx = pd.DataFrame({"caldt": ts, "vwretx": [0.0, 0.0, 0.0]})
    months = np.asarray(pd.to_datetime(["2000-01-31"]))
    cd = build_compact_daily(crsp_d, idx, months)
    assert cd.n_days == 3  # two same-day timestamps remain distinct
    assert list(cd.row_pos) == [0, 1, 2]
    np.testing.assert_array_equal(np.asarray(cd.days), np.asarray(ts))


def test_beta_all_null_market_window_nan(rng):
    """A window whose rows all lack market returns has cov = var = 0 exactly
    (polars: 0/0 = null); the cumsum-difference residuals must not turn it
    into an arbitrary finite beta."""
    d_days, n_firms = 120, 3
    ret = rng.standard_normal((d_days, n_firms)) * 0.02
    mask = np.ones((d_days, n_firms), bool)
    # market: present every day, but returns null for the first 60 days
    mkt = rng.standard_normal(d_days) * 0.01
    mkt[:60] = np.nan
    mkt_present = np.ones(d_days, bool)
    # firm 2 only exists in the null-market regime
    mask[60:, 2] = False
    month_id = np.repeat(np.arange(6), 20).astype(np.int32)
    week_id = (np.arange(d_days) // 5).astype(np.int32)
    n_weeks = int(week_id.max()) + 1
    week_month_id = np.clip(np.arange(n_weeks) // 4, 0, 5).astype(np.int32)

    beta = weekly_rolling_beta_monthly(
        jnp.asarray(ret), jnp.asarray(mask), jnp.asarray(mkt),
        jnp.asarray(week_id), n_weeks, jnp.asarray(week_month_id), 6,
        window_weeks=6, mkt_present=jnp.asarray(mkt_present),
    )
    b = np.asarray(beta)
    # firm 2's windows never contain a market return → NaN everywhere
    assert np.isnan(b[:, 2]).all()
    # firms 0/1 have data-bearing windows late in the sample → some finite
    assert np.isfinite(b[:, :2]).any()


def test_chunked_auto_path_runs(rng, monkeypatch):
    """Auto heuristic with a tiny budget must force multi-strip execution and
    still match the single call."""
    monkeypatch.setenv("FMRP_DAILY_BUDGET_BYTES", "200000")
    d = _daily_fixture(rng)
    vol0, beta0 = _unchunked(d)
    vol, beta = daily_characteristics_chunked(
        **d, window=60, min_periods=20, window_weeks=26
    )
    np.testing.assert_array_equal(vol, vol0)
    np.testing.assert_array_equal(beta, beta0)
