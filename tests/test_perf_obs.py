"""Performance-observability plane: cost ledger, profiler hooks, flight
recorder, recompile sentinel, SLO monitor, Prometheus edge cases.

Acceptance contract (ISSUE 6):

- the COST LEDGER records cost_analysis/memory_analysis + compile wall
  time for every AOT program in the serving and specgrid paths, and the
  records ride the existing exporters (JSONL ``program`` lines, Chrome
  counter tracks, ``fmrp_program_*`` families);
- the FLIGHT RECORDER freezes the last spans/events + ledger tail to
  ``flight.json`` on serving quarantine (and is a safe no-op unarmed);
- the RECOMPILE SENTINEL turns warm-run persistent-cache growth into a
  counted, attributed warning;
- SLO state transitions ok→warn→breach→recover are a pure function of a
  deterministic synthetic latency stream, and are visible through
  ``stats()`` and ``prometheus_metrics()``;
- the Prometheus text format survives hostile label values, concurrent
  histogram updates, and serves the right content type.
"""

import json
import threading

import numpy as np
import pytest

from fm_returnprediction_tpu import telemetry
from fm_returnprediction_tpu.telemetry import metrics as tmetrics
from fm_returnprediction_tpu.telemetry import perf as tperf
from fm_returnprediction_tpu.telemetry import slo as tslo

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.set_trace_dir(None)
    yield
    telemetry.reset()
    telemetry.set_trace_dir(None)


def _serving_state(t=24, n=40, p=4, seed=5):
    from fm_returnprediction_tpu.serving import build_serving_state

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = (0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    return build_serving_state(y, x, mask, window=12, min_periods=6), x


# -- cost ledger ------------------------------------------------------------


def test_timed_aot_compile_records_cost_and_memory():
    import jax
    import jax.numpy as jnp

    before = len(tperf.cost_ledger().records())
    f = jax.jit(lambda a: jnp.sum(a @ a.T))
    compiled = tperf.timed_aot_compile(
        f, jnp.zeros((16, 16), jnp.float32), program="obs_test_probe"
    )
    assert float(compiled(jnp.zeros((16, 16), jnp.float32))) == 0.0
    records = [
        r for r in tperf.cost_ledger().records()[before:]
        if r.program == "obs_test_probe"
    ]
    assert len(records) == 1
    (r,) = records
    assert r.compile_s > 0 and r.lower_s > 0
    assert r.signature and r.fingerprint
    assert r.provenance in ("fresh", "persistent-cache", "uncached")
    # CPU XLA supports both analyses; if a backend ever stops, the field
    # goes None rather than the compile failing — assert the happy path
    assert r.flops is not None and r.flops > 0
    assert r.temp_bytes is not None
    # registry families materialized
    collected = telemetry.registry().collect()
    assert any(
        ("program", "obs_test_probe") in dict(k)
        or dict(k).get("program") == "obs_test_probe"
        for k in collected["fmrp_program_compiles_total"]
    )


def test_serving_executor_buckets_land_in_ledger():
    from fm_returnprediction_tpu.serving.executor import BucketedExecutor

    state, _ = _serving_state()
    mark = tperf.cost_ledger().last_seq
    exe = BucketedExecutor(state, max_batch=8)
    exe.warmup()
    new = [
        r for r in tperf.cost_ledger().since(mark)
        if r.program == "serving_bucket"
    ]
    assert {r.bucket for r in new} == set(exe.buckets())
    for r in new:
        assert r.compile_s > 0
        assert r.flops is not None


def test_specgrid_program_lands_in_ledger_once_per_signature():
    from fm_returnprediction_tpu import specgrid

    rng = np.random.default_rng(0)
    t, n, p = 24, 30, 3
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = (x @ (0.05 * rng.standard_normal(p)).astype(np.float32)
         + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    masks = {"All": rng.random((t, n)) > 0.2}
    names = [f"x{i}" for i in range(p)]
    grid = specgrid.SpecGrid(
        (specgrid.Spec("m | All", tuple(names), "All"),)
    )

    def grid_records():
        return [
            r for r in tperf.cost_ledger().records()
            if r.program == "specgrid_program"
        ]

    before = len(grid_records())
    specgrid.run_spec_grid(y, x, masks, grid)
    after_first = len(grid_records())
    specgrid.run_spec_grid(y, x, masks, grid)
    after_second = len(grid_records())
    # exactly one ledger record for a new signature, zero for the repeat
    # (the AOT cache, like jit's, compiles once per signature)
    assert after_first - before == 1
    assert after_second == after_first
    rec = grid_records()[-1]
    assert rec.compile_s > 0 and rec.flops is not None


def test_program_records_ride_the_exporters(tmp_path):
    import jax
    import jax.numpy as jnp

    tperf.timed_aot_compile(
        jax.jit(lambda a: a * 2.0), jnp.zeros((4,), jnp.float32),
        program="obs_export_probe",
    )
    from fm_returnprediction_tpu.telemetry import export

    jsonl = export.write_jsonl(tmp_path / "events.jsonl")
    records = [json.loads(line) for line in jsonl.read_text().splitlines()]
    programs = [r for r in records if r["type"] == "program"]
    assert any(p["program"] == "obs_export_probe" for p in programs)
    probe = next(p for p in programs if p["program"] == "obs_export_probe")
    for key in ("flops", "bytes_accessed", "compile_s", "lower_s",
                "provenance", "fingerprint", "signature", "ts_us"):
        assert key in probe
    # deterministic re-export stays byte-identical with ledger records
    again = export.write_jsonl(tmp_path / "events2.jsonl")
    assert jsonl.read_bytes() == again.read_bytes()

    chrome = json.loads(
        export.write_chrome_trace(tmp_path / "trace.json").read_text()
    )
    events = chrome["traceEvents"]
    counters = [e for e in events if e["ph"] == "C"]
    assert any(e["name"] == "program_flops" for e in counters)
    compiles = [
        e for e in events
        if e["ph"] == "X" and e["name"].startswith("compile:")
    ]
    assert any(e["name"] == "compile:obs_export_probe" for e in compiles)
    # the dedicated compile row is named
    assert any(
        e["ph"] == "M" and e["name"] == "thread_name"
        and e["args"]["name"] == "fmrp-compiles"
        for e in events
    )


def test_record_runtime_sets_roofline_gauges():
    import jax
    import jax.numpy as jnp

    tperf.timed_aot_compile(
        jax.jit(lambda a: jnp.sum(a @ a.T)), jnp.zeros((32, 32), jnp.float32),
        program="obs_roofline_probe",
    )
    out = telemetry.record_runtime("obs_roofline_probe", 0.01)
    assert out["achieved_flops"] > 0
    assert 0 <= out["roofline_utilization"]
    text = telemetry.registry().to_prometheus()
    assert 'fmrp_program_achieved_flops{program="obs_roofline_probe"}' in text
    # no ledger FLOPs → empty dict, no crash
    assert telemetry.record_runtime("does_not_exist", 1.0) == {}


# -- flight recorder --------------------------------------------------------


def test_dump_flight_unarmed_is_noop_and_armed_writes(tmp_path):
    assert telemetry.dump_flight("test.reason") is None  # no trace dir
    with telemetry.enabled(True):
        with telemetry.span("flight_parent"):
            telemetry.event("flight_tick")
    telemetry.set_trace_dir(tmp_path)
    path = telemetry.dump_flight("test.reason")
    assert path is not None and path.name == "flight.json"
    doc = json.loads(path.read_text())
    assert doc["type"] == "flight" and doc["reason"] == "test.reason"
    assert any(s["name"] == "flight_parent" for s in doc["spans"])
    assert "programs" in doc and "metrics" in doc and "collector" in doc


def test_quarantine_dumps_flight(tmp_path):
    from fm_returnprediction_tpu.serving import ERService

    state, x = _serving_state()
    telemetry.set_trace_dir(tmp_path)
    with telemetry.enabled(True):
        with ERService(state, max_batch=8, warm=True,
                       auto_flush=False) as svc:
            bad = np.full((40, x.shape[-1]), np.nan, dtype=np.float32)
            ok = svc.ingest_month(
                np.full(40, np.nan), bad, np.ones(40, bool),
                np.datetime64("2071-01-31", "ns"),
            )
            assert not ok and svc.degraded
    flight = tmp_path / "flight.json"
    assert flight.exists()
    doc = json.loads(flight.read_text())
    assert doc["reason"].startswith("serving.quarantine:")


# -- recompile sentinel -----------------------------------------------------


class _FakeCompiled:
    def cost_analysis(self):
        return [{"flops": 123.0, "bytes accessed": 456.0}]

    def memory_analysis(self):
        raise NotImplementedError  # memory fields go None, no crash


def test_recompile_watch_counts_and_attributes(monkeypatch):
    entries = iter([10, 12])  # watch-open, watch-close

    monkeypatch.setattr(
        tmetrics, "jax_cache_stats",
        lambda cache_dir=None: {"entries": next(entries), "bytes": 0},
    )
    counter = telemetry.registry().counter(
        "fmrp_unexpected_recompiles_total", section="warm_probe"
    )
    base = counter.value
    with pytest.warns(UserWarning, match="warm region 'warm_probe' grew"):
        with telemetry.recompile_watch("warm_probe", warm=True) as delta:
            tperf.record_compiled(
                "warm_probe_prog", _FakeCompiled(), "sig", 0.1, 0.2,
                cache_entries_delta=2, cache_enabled=True,
            )
    assert delta.grew == 2
    assert any("warm_probe_prog@" in c for c in delta.culprits)
    assert counter.value == base + 2
    rec = [
        r for r in tperf.cost_ledger().records()
        if r.program == "warm_probe_prog"
    ][-1]
    assert rec.provenance == "fresh"
    assert rec.flops == 123.0 and rec.temp_bytes is None


def test_recompile_watch_cold_region_never_warns(monkeypatch):
    entries = iter([10, 12])
    monkeypatch.setattr(
        tmetrics, "jax_cache_stats",
        lambda cache_dir=None: {"entries": next(entries), "bytes": 0},
    )
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")  # any warning fails the test
        with telemetry.recompile_watch("cold_probe", warm=False) as delta:
            pass
    assert delta.grew == 2  # recorded, not warned


# -- profiler hooks ---------------------------------------------------------


def test_profiling_arms_span_annotations(tmp_path):
    import jax
    import jax.numpy as jnp

    assert not tperf.profiler_active()
    assert not telemetry.active()
    with telemetry.profiling(tmp_path / "prof"):
        assert tperf.profiler_active()
        # --profile-dir alone must annotate: the capture region arms span
        # collection even when telemetry is otherwise off
        assert telemetry.active()
        with telemetry.span("profiled_region"):
            float(jax.jit(lambda a: jnp.sum(a))(jnp.ones(8)))
        # nesting refused, outer capture intact
        with pytest.raises(RuntimeError, match="already active"):
            with telemetry.profiling(tmp_path / "prof2"):
                pass
    assert not tperf.profiler_active()
    # the capture produced an artifact directory
    assert (tmp_path / "prof").exists()
    assert any((tmp_path / "prof").rglob("*"))
    # passthrough mode: no arming, no error
    with telemetry.profiling(None):
        assert not tperf.profiler_active()
    # the span recorded normally despite the annotation mirror
    assert any(
        s.name == "profiled_region" for s in telemetry.finished_spans()
    )


# -- SLO monitor ------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_slo_state_transitions_ok_warn_breach_recover():
    clock = _Clock()
    slo = tslo.SLO("p99", "latency", threshold_ms=10.0, budget=0.10,
                   warn_burn=1.0, breach_burn=2.0)
    mon = tslo.SloMonitor((slo,), window_s=10.0, clock=clock)

    # 100 fast requests → ok
    for _ in range(100):
        mon.observe(0.001)
    assert mon.snapshot()["objectives"]["p99"]["state"] == "ok"

    # 12% slow → burn 1.2 → warn
    for _ in range(12):
        mon.observe(0.050)
    snap = mon.snapshot()
    assert snap["objectives"]["p99"]["state"] == "warn"
    assert snap["state"] == "warn" and snap["state_code"] == 1

    # pile on: 30% slow → burn ≥ 2 → breach
    for _ in range(30):
        mon.observe(0.050)
    snap = mon.snapshot()
    assert snap["objectives"]["p99"]["state"] == "breach"
    assert snap["state_code"] == 2

    # the window drains: advance past it, healthy traffic → recover to ok
    clock.t += 11.0
    for _ in range(20):
        mon.observe(0.001)
    snap = mon.snapshot()
    assert snap["objectives"]["p99"]["state"] == "ok"
    assert snap["n"] == 20  # aged-out samples really left the window


def test_slo_error_rate_and_reject_samples():
    clock = _Clock()
    slo = tslo.SLO("errors", "error_rate", budget=0.05)
    mon = tslo.SloMonitor((slo,), window_s=60.0, clock=clock)
    for _ in range(95):
        mon.observe(0.001, ok=True)
    for _ in range(5):
        mon.observe(None, ok=False)  # rejects carry no latency
    snap = mon.snapshot()
    assert snap["error_rate"] == pytest.approx(0.05)
    assert snap["objectives"]["errors"]["burn_rate"] == pytest.approx(1.0)
    assert snap["objectives"]["errors"]["state"] == "warn"
    # latency quantiles ignore the NaN (reject) samples
    assert snap["p99_ms"] is not None


def test_slo_queue_breach_is_reachable():
    # queue burn is continuous (occupancy / ceiling), so a saturated
    # queue must be able to reach breach — a binary trip capped burn at
    # 1.0 and left breach unreachable for any ceiling above 0.5
    slo = tslo.slos_from_env({"FMRP_SLO_QUEUE": "0.8"})[0]
    mon = tslo.SloMonitor((slo,), window_s=60.0, clock=_Clock())
    mon.observe_queue(0.5)
    assert mon.snapshot()["objectives"]["queue_occupancy"]["state"] == "ok"
    mon.observe_queue(0.7)  # 87.5% of the ceiling → warn
    assert mon.snapshot()["objectives"]["queue_occupancy"]["state"] == "warn"
    mon.observe_queue(0.95)  # over the ceiling → breach
    snap = mon.snapshot()["objectives"]["queue_occupancy"]
    assert snap["state"] == "breach"
    assert snap["burn_rate"] == pytest.approx(0.95 / 0.8)


def test_slo_validation():
    with pytest.raises(ValueError, match="kind"):
        tslo.SLO("x", "bogus")
    with pytest.raises(ValueError, match="threshold_ms"):
        tslo.SLO("x", "latency")
    with pytest.raises(ValueError, match="budget"):
        tslo.SLO("x", "error_rate", budget=0.0)
    with pytest.raises(ValueError, match="warn"):
        tslo.SLO("x", "latency", threshold_ms=1.0,
                 warn_burn=2.0, breach_burn=1.0)
    with pytest.raises(ValueError, match="duplicate"):
        tslo.SloMonitor((
            tslo.SLO("a", "error_rate", budget=0.1),
            tslo.SLO("a", "error_rate", budget=0.2),
        ))


def test_slos_from_env():
    env = {
        "FMRP_SLO_P99_MS": "25",
        "FMRP_SLO_ERROR_RATE": "0.02",
        "FMRP_SLO_QUEUE": "0.9",
        "FMRP_SLO_WARN_BURN": "0.5",
    }
    slos = tslo.slos_from_env(env)
    assert {s.name for s in slos} == {
        "p99_latency", "error_rate", "queue_occupancy"
    }
    p99 = next(s for s in slos if s.name == "p99_latency")
    assert p99.threshold_ms == 25.0 and p99.budget == 0.01
    assert p99.warn_burn == 0.5
    assert tslo.slos_from_env({}) == ()


def test_erservice_slo_in_stats_and_metrics():
    from fm_returnprediction_tpu.serving import ERService

    state, x = _serving_state()
    t = 24
    slos = (tslo.SLO("p99_latency", "latency", threshold_ms=1e4),)
    with ERService(state, max_batch=8, warm=True, auto_flush=False,
                   slos=slos) as svc:
        svc.submit(t - 1, x[t - 1, 0])
        svc.batcher.drain()
        stats = svc.stats()
        assert stats["slo_state"] == "ok"
        assert stats["slo_state_code"] == 0
        assert stats["slo"]["p99_latency"]["burn_rate"] == 0.0
        text = svc.prometheus_metrics()
    assert 'fmrp_slo_state{slo="p99_latency"} 0' in text
    assert 'fmrp_slo_burn_rate{slo="p99_latency"}' in text
    assert "fmrp_serving_service_slo_state_code 0" in text


def test_erservice_without_slos_reports_none():
    from fm_returnprediction_tpu.serving import ERService

    state, x = _serving_state()
    with ERService(state, max_batch=8, warm=True, auto_flush=False) as svc:
        stats = svc.stats()
        assert stats["slo_state"] is None
        assert "slo" not in stats


# -- Prometheus text-format edge cases --------------------------------------


def test_label_values_are_escaped():
    reg = telemetry.registry()
    hostile = 'say "hi"\\path\nnewline'
    reg.counter(
        "fmrp_test_escape_total", help="escape probe", detail=hostile
    ).inc()
    text = reg.to_prometheus()
    (line,) = [
        l for l in text.splitlines()
        if l.startswith("fmrp_test_escape_total{")
    ]
    # escaped per exposition format: \" \\ \n — and ONE physical line
    assert '\\"hi\\"' in line
    assert "\\\\path" in line
    assert "\\nnewline" in line
    assert "\n" not in line


def test_help_lines_are_escaped():
    reg = telemetry.registry()
    reg.counter("fmrp_test_help_total", help="line1\nline2 \\ slash").inc()
    text = reg.to_prometheus()
    (help_line,) = [
        l for l in text.splitlines()
        if l.startswith("# HELP fmrp_test_help_total")
    ]
    assert help_line == "# HELP fmrp_test_help_total line1\\nline2 \\\\ slash"


def _parse_histogram(text, name):
    buckets, hsum, count = [], None, None
    for line in text.splitlines():
        if line.startswith(f"{name}_bucket"):
            buckets.append(float(line.rsplit(" ", 1)[1]))
        elif line.startswith(f"{name}_sum"):
            hsum = float(line.rsplit(" ", 1)[1])
        elif line.startswith(f"{name}_count"):
            count = float(line.rsplit(" ", 1)[1])
    return buckets, hsum, count


def test_histogram_rendering_under_concurrent_updates():
    reg = telemetry.registry()
    hist = reg.histogram(
        "fmrp_test_concurrent_seconds", buckets=(0.001, 0.01, 0.1, 1.0)
    )
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            hist.observe(0.005)
            hist.observe(0.5)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(20):
            text = reg.to_prometheus()
            buckets, hsum, count = _parse_histogram(
                text, "fmrp_test_concurrent_seconds"
            )
            assert len(buckets) == 5  # 4 bounds + +Inf
            # cumulative buckets are monotone and +Inf equals count —
            # a torn read would violate one of these
            assert buckets == sorted(buckets)
            assert buckets[-1] == count
            assert hsum >= 0
    finally:
        stop.set()
        for th in threads:
            th.join()


def test_metrics_server_content_type_and_help_type_lines():
    from fm_returnprediction_tpu.serving import ERService

    state, x = _serving_state()
    t = 24
    with ERService(state, max_batch=8, warm=True, auto_flush=False) as svc:
        svc.submit(t - 1, x[t - 1, 0])
        svc.batcher.drain()
        host, port = svc.start_metrics_server()
        import urllib.request

        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == (
                "text/plain; version=0.0.4"
            )
            body = resp.read().decode()
    lines = body.splitlines()
    # every family: HELP (when present) immediately precedes TYPE, TYPE
    # precedes its samples, and TYPE values are legal
    seen_type = {}
    for i, line in enumerate(lines):
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in seen_type  # one TYPE per family
            seen_type[name] = i
            if i and lines[i - 1].startswith("# HELP "):
                assert lines[i - 1].split(" ")[2] == name
        elif line and not line.startswith("#"):
            metric = line.split("{", 1)[0].split(" ", 1)[0]
            family = metric
            for suffix in ("_bucket", "_sum", "_count"):
                if metric.endswith(suffix) and metric[: -len(suffix)] in seen_type:
                    family = metric[: -len(suffix)]
                    break
            if family in seen_type:
                assert seen_type[family] < i  # TYPE precedes samples
    assert "fmrp_serving_requests_done_total" in seen_type
    assert "fmrp_serving_request_latency_seconds" in seen_type
