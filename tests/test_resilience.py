"""Unit tests for the resilience layer's building blocks.

Policy math (deterministic jitter), retry loop semantics (allowlist,
exhaustion, injectable sleep), fault-plan determinism and nesting, atomic
cache writes, checksum-on-load, and the stage checkpointer's
load/recompute/invalidate contract. The end-to-end recovery paths live in
``tests/test_chaos.py``.
"""

import os

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.resilience import (
    CorruptArtifactError,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    RetryExhaustedError,
    RetryPolicy,
    StageCheckpointer,
    call_with_retry,
    fault_site,
)
from fm_returnprediction_tpu.utils import cache


# -- retry policy ----------------------------------------------------------

def test_delay_schedule_deterministic_and_bounded():
    pol = RetryPolicy(backoff_s=1.0, multiplier=2.0, max_backoff_s=5.0,
                      jitter=0.25, seed=7)
    delays = [pol.delay_s(k, "site") for k in range(1, 6)]
    assert delays == [pol.delay_s(k, "site") for k in range(1, 6)]  # pure
    for k, d in enumerate(delays, start=1):
        base = min(1.0 * 2.0 ** (k - 1), 5.0)
        assert base * 0.75 <= d <= base * 1.25
    # a different label/seed jitters differently (retrier spreading)
    assert pol.delay_s(1, "site") != pol.delay_s(1, "other")
    assert RetryPolicy(jitter=0.0).delay_s(3) == pytest.approx(0.4)


def test_retry_allowlist_and_exhaustion():
    calls = {"n": 0}

    def flaky(budget):
        def fn():
            calls["n"] += 1
            if calls["n"] < budget:
                raise OSError("transient")
            return "ok"
        return fn

    slept = []
    pol = RetryPolicy(max_attempts=3, backoff_s=0.01, jitter=0.0)
    assert call_with_retry(flaky(3), pol, sleep=slept.append) == "ok"
    assert calls["n"] == 3 and len(slept) == 2

    # non-allowlisted errors propagate untouched, first try
    calls["n"] = 0
    with pytest.raises(KeyError):
        call_with_retry(lambda: (_ for _ in ()).throw(KeyError("x")), pol,
                        sleep=slept.append)

    # exhaustion raises the typed error with the last failure as cause
    with pytest.raises(RetryExhaustedError, match="after 2 attempts") as exc:
        call_with_retry(
            lambda: (_ for _ in ()).throw(OSError("down")),
            RetryPolicy(max_attempts=2, backoff_s=0.0),
            label="pull", sleep=lambda s: None,
        )
    assert isinstance(exc.value.__cause__, OSError)


def test_on_retry_callback_sees_each_failure():
    seen = []
    with pytest.raises(RetryExhaustedError):
        call_with_retry(
            lambda: (_ for _ in ()).throw(OSError("x")),
            RetryPolicy(max_attempts=3, backoff_s=0.0),
            sleep=lambda s: None,
            on_retry=lambda n, err: seen.append(n),
        )
    assert seen == [1, 2]  # no callback after the final attempt


# -- fault plan ------------------------------------------------------------

def test_fault_site_noop_without_plan():
    payload = object()
    assert fault_site("anything", payload=payload) is payload


def test_fault_plan_times_skip_and_heal():
    spec = FaultSpec(times=2, skip=1)
    with FaultPlan({"s": spec}) as plan:
        fault_site("s")                      # call 1: skipped
        for _ in range(2):                   # calls 2-3: fire
            with pytest.raises(InjectedFault):
                fault_site("s")
        fault_site("s")                      # call 4: healed
    assert plan.calls["s"] == 4 and plan.fired["s"] == 2


def test_fault_plan_probability_deterministic():
    def fired_pattern(seed):
        with FaultPlan({"p": FaultSpec(times=-1, probability=0.5)},
                       seed=seed) as plan:
            out = []
            for _ in range(20):
                try:
                    fault_site("p")
                    out.append(False)
                except InjectedFault:
                    out.append(True)
        return out

    a, b = fired_pattern(3), fired_pattern(3)
    assert a == b                      # same seed → same chaos
    assert any(a) and not all(a)       # p=0.5 over 20 calls does both
    assert fired_pattern(4) != a       # a different seed differs


def test_fault_plan_mutate_and_nesting():
    outer = FaultPlan({"x": FaultSpec(times=-1)})
    inner = FaultPlan(
        {"x": FaultSpec(times=-1, mutate=lambda p: p + 1)}
    )
    with outer:
        with inner:
            assert fault_site("x", payload=1) == 2  # inner poisons
        with pytest.raises(InjectedFault):
            fault_site("x")                          # outer restored
    assert fault_site("x", payload=1) == 1           # uninstalled


def test_fault_plan_delay_only_stalls_without_raising():
    import time

    with FaultPlan({"slow": FaultSpec(times=1, delay_s=0.05)}):
        t0 = time.perf_counter()
        assert fault_site("slow", payload="p") == "p"
        assert time.perf_counter() - t0 >= 0.05


# -- atomic cache writes ---------------------------------------------------

def test_write_cache_data_is_atomic_on_failure(tmp_path, monkeypatch):
    """A writer crash mid-write must leave the OLD file intact and no temp
    litter — never a truncated parquet that poisons the next run."""
    path = tmp_path / "x.parquet"
    cache.write_cache_data(pd.DataFrame({"a": [1]}), path)

    def torn_write(self, fp, index=False):
        with open(fp, "wb") as f:
            f.write(b"PAR1garbage")
        raise OSError("disk full")

    monkeypatch.setattr(pd.DataFrame, "to_parquet", torn_write)
    with pytest.raises(OSError):
        cache.write_cache_data(pd.DataFrame({"a": [1, 2]}), path)
    monkeypatch.undo()
    out = cache.read_cached_data(path)          # old content survives
    assert list(out["a"]) == [1]
    assert [f for f in os.listdir(tmp_path) if "tmp" in f] == []


def test_save_array_bundle_atomic_and_no_tmp_litter(tmp_path):
    p = cache.save_array_bundle(tmp_path / "b", {"a": np.arange(4.0)})
    assert p.suffix == ".npz"
    assert [f for f in os.listdir(tmp_path) if "tmp" in f] == []


# -- checksum-on-load ------------------------------------------------------

def test_bundle_checksum_roundtrip_and_corruption(tmp_path):
    arrays = {"a": np.arange(6.0).reshape(2, 3), "b": np.array([1, 2, 3])}
    p = cache.save_array_bundle(tmp_path / "b", arrays, {"k": "v"})
    got, meta = cache.load_array_bundle(p)
    assert meta == {"k": "v"}  # the stored hash never leaks into meta
    np.testing.assert_array_equal(got["a"], arrays["a"])

    # truncation (torn write shape) → typed error, not a numpy crash
    data = p.read_bytes()
    p.write_bytes(data[: len(data) // 2])
    with pytest.raises(CorruptArtifactError):
        cache.load_array_bundle(p)

    # a flipped payload byte in an intact zip container → hash mismatch
    p2 = cache.save_array_bundle(tmp_path / "c", arrays)
    raw = bytearray(p2.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    p2.write_bytes(bytes(raw))
    with pytest.raises(CorruptArtifactError):
        cache.load_array_bundle(p2)


def test_bundle_meta_hash_key_reserved(tmp_path):
    with pytest.raises(ValueError, match="reserved"):
        cache.save_array_bundle(
            tmp_path / "b", {"a": np.zeros(1)}, {"__sha256__": "spoof"}
        )


def test_pre_checksum_bundle_still_loads(tmp_path):
    """Bundles written before the checksum existed (no stored hash) load
    unverified — old artifacts must not be bricked by the upgrade."""
    import json

    p = tmp_path / "old.npz"
    np.savez_compressed(
        p, __meta__=np.asarray(json.dumps({"k": 1})), a=np.arange(3.0)
    )
    arrays, meta = cache.load_array_bundle(p)
    assert meta == {"k": 1} and "a" in arrays


# -- stage checkpointer ----------------------------------------------------

def test_checkpointer_load_or_compute(tmp_path):
    calls = {"n": 0}

    def compute():
        calls["n"] += 1
        return pd.DataFrame({"v": [calls["n"]]})

    ck = StageCheckpointer(tmp_path, "fp1")
    first = ck.frame("t", compute)
    assert calls["n"] == 1 and ck.completed("t")

    again = StageCheckpointer(tmp_path, "fp1").frame("t", compute)
    assert calls["n"] == 1                      # loaded, not recomputed
    pd.testing.assert_frame_equal(first, again)


def test_checkpointer_fingerprint_invalidates(tmp_path):
    calls = {"n": 0}

    def compute():
        calls["n"] += 1
        return pd.DataFrame({"v": [calls["n"]]})

    StageCheckpointer(tmp_path, "fp1").frame("t", compute)
    other = StageCheckpointer(tmp_path, "fp2")
    assert not other.completed("t")             # different data → invalid
    other.frame("t", compute)
    assert calls["n"] == 2


def test_checkpointer_corrupt_stage_recomputes(tmp_path):
    calls = {"n": 0}

    def compute():
        calls["n"] += 1
        return pd.DataFrame({"v": [7]})

    ck = StageCheckpointer(tmp_path, "fp")
    ck.frame("t", compute)
    (tmp_path / "t.pkl").write_bytes(b"garbage")  # bit-rot / torn write
    with pytest.warns(UserWarning, match="recomputing"):
        out = StageCheckpointer(tmp_path, "fp").frame("t", compute)
    assert calls["n"] == 2 and list(out["v"]) == [7]
