"""Online serving subsystem vs the batch forecast oracle.

The serving contract is differential: a streamed, microbatched, padded,
thread-interleaved sequence of single-firm queries must reproduce the batch
``rolling_er_forecast`` projection exactly (1e-6 over the acceptance
tolerance; asserted far tighter here), including firms with incomplete
predictors (NaN, never a padded-garbage value); incremental month ingest
must match a full refit; and after warm-up the executable cache must serve
every dispatch (no query-time compiles — asserted through the service's
own counters).
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from fm_returnprediction_tpu.models.forecast import rolling_er_forecast
from fm_returnprediction_tpu.serving import (
    ERService,
    ServingState,
    build_serving_state,
    ingest_month,
)

WINDOW, MIN_PERIODS = 40, 20


def _make_panel(rng, t=120, n=80, p=3, signal=0.05, nan_features=True):
    x = rng.standard_normal((t, n, p))
    beta = signal * np.array([1.0, -0.5, 0.25])[:p]
    y = x @ beta + 0.02 * rng.standard_normal((t, n))
    mask = rng.random((t, n)) > 0.1
    y = np.where(mask, y, np.nan)
    x = np.where(mask[..., None], x, np.nan)
    if nan_features:
        # firms with incomplete predictors INSIDE the mask: one feature NaN
        holes = rng.random((t, n)) < 0.05
        x[..., 0] = np.where(holes & mask, np.nan, x[..., 0])
    return y, x, mask


@pytest.fixture(scope="module")
def case():
    rng = np.random.default_rng(2015)
    y, x, mask = _make_panel(rng)
    fr = rolling_er_forecast(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask),
        window=WINDOW, min_periods=MIN_PERIODS,
    )
    state = build_serving_state(
        y, x, mask, window=WINDOW, min_periods=MIN_PERIODS
    )
    return y, x, mask, np.asarray(fr.er), np.asarray(fr.slopes_bar), state


def test_state_matches_batch_artifacts(case):
    _, _, _, _, slopes_bar, state = case
    np.testing.assert_allclose(
        state.slopes_bar, slopes_bar, rtol=1e-12, equal_nan=True
    )
    assert state.coef.shape == (120, 4)
    assert state.gram.shape == (120, 4, 4)


def test_microbatched_stream_matches_batch_forecast(case):
    """Random single-firm queries from several threads, coalesced by the
    live batcher, equal the batch projection — NaN rows included."""
    _, x, _, er, _, state = case
    rng = np.random.default_rng(7)
    t, n = er.shape
    pairs = [
        (int(rng.integers(0, t)), int(rng.integers(0, n))) for _ in range(400)
    ]
    got = np.empty(len(pairs))
    with ERService(state, max_batch=32, max_latency_ms=1.0) as svc:
        def worker(lo, hi):
            for k in range(lo, hi):
                tt, i = pairs[k]
                got[k] = svc.query(tt, x[tt, i])

        threads = [
            threading.Thread(target=worker, args=(k * 100, (k + 1) * 100))
            for k in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        stats = svc.stats()
    want = np.array([er[tt, i] for tt, i in pairs])
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12, equal_nan=True)
    # the stream genuinely exercised the serving path
    assert stats["n_done"] == len(pairs)
    assert stats["executable_cache_misses"] == 0  # warm=True precompiled


def test_incomplete_predictors_return_nan_not_padded_garbage(case):
    _, x, mask, er, _, state = case
    with ERService(state, max_batch=8, max_latency_ms=0.5) as svc:
        # a masked-out firm-month (features NaN) and an in-mask firm with a
        # NaN feature must both come back NaN
        t_q = 100
        nan_rows = np.nonzero(~np.isfinite(er[t_q]))[0]
        assert len(nan_rows), "fixture must contain unavailable rows"
        for i in nan_rows[:5]:
            assert np.isnan(svc.query(t_q, x[t_q, i]))
        # an explicit all-NaN feature row is unavailable too
        assert np.isnan(svc.query(t_q, np.full(state.n_predictors, np.nan)))


def test_serving_answers_rows_with_missing_realized_return(case):
    """DELIBERATE superset of the batch gate (executor docstring): the
    batch ``er_valid`` additionally requires the row's REALIZED return to
    be finite because its rows feed decile sorts, but serving quotes E[r]
    at the start of a month — before the realized return can exist — so a
    features-complete row with missing y is answerable, and the answer is
    exactly the projection the batch would make."""
    y, x, mask, er, _, state = case
    t_q = 110
    assert state.have_coef()[t_q]
    rows = np.nonzero(
        mask[t_q] & ~np.isfinite(y[t_q]) | (
            mask[t_q] & np.all(np.isfinite(x[t_q]), axis=1)
        )
    )[0]
    # a live-quote row: complete predictors, NO realized return
    x_row = x[t_q, rows[0]].copy()
    x_row = np.where(np.isfinite(x_row), x_row, 0.0)  # force complete
    expected = state.intercept_bar[t_q] + float(
        np.clip(x_row, state.x_lo[t_q], state.x_hi[t_q]) @ state.slopes_bar[t_q]
    )
    with ERService(state, max_batch=8, max_latency_ms=0.5) as svc:
        got = svc.query(t_q, x_row)
    np.testing.assert_allclose(got, expected, rtol=1e-12)
    # and wherever the batch IS defined, serving agrees (the differential
    # tests pin this panel-wide; this is the superset's boundary)
    finite_cells = np.nonzero(np.isfinite(er[t_q]))[0]
    assert len(finite_cells)


def test_months_before_min_periods_are_unavailable(case):
    _, x, _, er, _, state = case
    assert not state.have_coef()[:MIN_PERIODS].any()
    with ERService(state, max_batch=8, max_latency_ms=0.5) as svc:
        assert np.isnan(svc.query(0, np.zeros(state.n_predictors)))


def test_unknown_month_raises(case):
    *_, state = case
    with ERService(state, warm=False, auto_flush=False) as svc:
        with pytest.raises(KeyError):
            svc.query(np.datetime64("1901-01-01"), np.zeros(3))
        with pytest.raises(KeyError):
            svc.query(10_000, np.zeros(3))


def test_no_compiles_after_warmup_over_1k_query_stream(case):
    """Acceptance criterion: a 1k-query synthetic stream with varying batch
    sizes hits the executable cache on EVERY dispatch after warm-up —
    asserted via the service's own counters."""
    _, x, _, er, _, state = case
    rng = np.random.default_rng(11)
    t, n = er.shape
    with ERService(state, max_batch=32, max_latency_ms=0.2) as svc:
        warm_compiles = svc.executor.compiles
        assert warm_compiles == len(svc.executor.buckets())
        assert svc.executor.misses == 0
        served = 0
        while served < 1000:
            k = int(rng.integers(1, 50))  # varying burst sizes
            months = rng.integers(0, t, k)
            rows = rng.integers(0, n, k)
            svc.query_many(list(months), [x[tt, i] for tt, i in zip(months, rows)])
            served += k
        stats = svc.stats()
    assert stats["n_done"] == served >= 1000
    assert stats["executable_cache_misses"] == 0
    assert svc.executor.compiles == warm_compiles  # nothing new compiled
    assert stats["executable_cache_hits"] == stats["n_batches"] > 0


def test_ingest_matches_full_refit(case):
    """Acceptance criterion: ingesting months one at a time from sufficient
    statistics matches a full ``rolling_er_forecast`` refit (1e-6; asserted
    tighter) — coefficients, lagged means, AND the queried projections."""
    y, x, mask, _, _, _ = case
    t0, t = 90, y.shape[0]
    state = build_serving_state(
        y[:t0], x[:t0], mask[:t0], window=WINDOW, min_periods=MIN_PERIODS,
        solver="normal",
    )
    for tt in range(t0, t):
        state = ingest_month(
            state, y[tt], x[tt], mask[tt], np.datetime64(tt, "M")
        )
    full = rolling_er_forecast(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask),
        window=WINDOW, min_periods=MIN_PERIODS, solver="normal",
    )
    np.testing.assert_allclose(
        state.slopes_bar, np.asarray(full.slopes_bar),
        rtol=1e-9, atol=1e-12, equal_nan=True,
    )
    np.testing.assert_allclose(
        state.intercept_bar, np.asarray(full.intercept_bar),
        rtol=1e-9, atol=1e-12, equal_nan=True,
    )
    # rebuild-from-scratch equals the ingested state (bounds, stats, coef)
    rebuilt = build_serving_state(
        y, x, mask, window=WINDOW, min_periods=MIN_PERIODS, solver="normal"
    )
    np.testing.assert_allclose(
        state.coef, rebuilt.coef, rtol=1e-9, atol=1e-12, equal_nan=True
    )
    np.testing.assert_allclose(
        state.x_lo, rebuilt.x_lo, rtol=1e-12, equal_nan=True
    )
    np.testing.assert_allclose(
        state.gram, rebuilt.gram, rtol=1e-9, atol=1e-12
    )
    # and the queries served off the ingested state match the batch er
    er_full = np.asarray(full.er)
    with ERService(state, max_batch=16, max_latency_ms=0.5) as svc:
        for tt in range(t0, t):
            for i in range(0, y.shape[1], 17):
                got = svc.query(tt, x[tt, i])
                want = er_full[tt, i]
                if np.isnan(want):
                    assert np.isnan(got)
                else:
                    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_ingest_same_month_stats_are_additive(case):
    """A month arriving in two disjoint pieces merges via stats addition to
    exactly the one-shot ingest."""
    y, x, mask, _, _, _ = case
    t0 = 90
    base = build_serving_state(
        y[:t0], x[:t0], mask[:t0], window=WINDOW, min_periods=MIN_PERIODS,
        solver="normal",
    )
    month = np.datetime64(t0, "M")
    half = y.shape[1] // 2
    m_a, m_b = mask[t0].copy(), mask[t0].copy()
    m_a[half:] = False
    m_b[:half] = False
    two = ingest_month(base, np.where(m_a, y[t0], np.nan), x[t0], m_a, month)
    two = ingest_month(two, np.where(m_b, y[t0], np.nan), x[t0], m_b, month)
    one = ingest_month(base, y[t0], x[t0], mask[t0], month)
    np.testing.assert_allclose(two.gram, one.gram, rtol=1e-12)
    np.testing.assert_allclose(two.moment, one.moment, rtol=1e-12)
    np.testing.assert_array_equal(two.n_obs, one.n_obs)
    np.testing.assert_allclose(
        two.coef, one.coef, rtol=1e-9, atol=1e-12, equal_nan=True
    )
    np.testing.assert_allclose(
        two.x_lo, one.x_lo, rtol=1e-12, equal_nan=True
    )
    np.testing.assert_allclose(
        two.x_hi, one.x_hi, rtol=1e-12, equal_nan=True
    )


def test_ingest_quote_for_month_without_returns(case):
    """The start-of-month use case (the superset's month level, executor
    docstring): ingest a new month whose returns do not exist yet — its
    own cross-section yields NO coefficient row — and the service must
    still quote E[r] there from strictly-prior months' coefficients; the
    bar must equal a full serving-state rebuild on the extended panel."""
    y, x, mask, _, _, _ = case
    base = build_serving_state(
        y, x, mask, window=WINDOW, min_periods=MIN_PERIODS
    )
    t, n, p = x.shape
    rng = np.random.default_rng(3)
    x_new = rng.standard_normal((n, p))
    y_new = np.full(n, np.nan)  # no realized returns yet
    month = np.datetime64(t, "M")
    state = ingest_month(base, y_new, x_new, np.ones(n, bool), month)
    assert not state.month_valid[-1]  # contributed no coefficient row ...
    assert state.have_coef()[-1]      # ... but the quote is available
    # the bar equals a full rebuild that sees the same y-less month
    rebuilt = build_serving_state(
        np.concatenate([y, y_new[None]]),
        np.concatenate([x, x_new[None]]),
        np.concatenate([mask, np.ones((1, n), bool)]),
        window=WINDOW, min_periods=MIN_PERIODS,
    )
    np.testing.assert_allclose(
        state.slopes_bar[-1], rebuilt.slopes_bar[-1], rtol=1e-6, atol=1e-9
    )
    with ERService(state, max_batch=8, max_latency_ms=0.5) as svc:
        got = svc.query(month, x_new[0])
    expected = state.intercept_bar[-1] + float(
        np.clip(x_new[0], state.x_lo[-1], state.x_hi[-1])
        @ state.slopes_bar[-1]
    )
    np.testing.assert_allclose(got, expected, rtol=1e-9)
    # once the returns arrive, the merge upgrades the month to a
    # coefficient row and the (prior-months-only) bar does not move
    y_real = x_new @ (0.05 * np.array([1.0, -0.5, 0.25])[:p])
    merged = ingest_month(state, y_real, x_new, np.ones(n, bool), month)
    assert merged.month_valid[-1]
    np.testing.assert_array_equal(merged.slopes_bar[-1], state.slopes_bar[-1])


def test_built_state_quotes_thin_months(case):
    """``build_serving_state`` applies the same month-level superset: a
    month with too few valid rows for its own OLS still gets the lagged
    mean of its strictly-prior surviving months."""
    y, x, mask, _, _, _ = case
    y2, x2, mask2 = y.copy(), x.copy(), mask.copy()
    t_thin = 110
    mask2[t_thin, 2:] = False  # 2 rows < Q=4: month cannot run its OLS
    y2[t_thin, 2:] = np.nan
    x2[t_thin, 2:] = np.nan
    state = build_serving_state(
        y2, x2, mask2, window=WINDOW, min_periods=MIN_PERIODS
    )
    assert not state.month_valid[t_thin]
    assert state.have_coef()[t_thin]
    # the batch forecast keeps its scatter convention (NaN there) — the
    # superset is serving-only
    fr = rolling_er_forecast(
        jnp.asarray(y2), jnp.asarray(x2), jnp.asarray(mask2),
        window=WINDOW, min_periods=MIN_PERIODS,
    )
    assert np.isnan(np.asarray(fr.slopes_bar)[t_thin]).all()
    # and the thin month's bar equals the NEXT surviving month's (same
    # prior window: the thin month contributed no row)
    t_next = t_thin + 1
    assert state.month_valid[t_next]
    np.testing.assert_array_equal(
        state.slopes_bar[t_thin], state.slopes_bar[t_next]
    )


def test_ingest_is_append_only(case):
    *_, state = case
    with pytest.raises(ValueError):
        ingest_month(
            state, np.zeros(3), np.zeros((3, 3)), np.ones(3, bool),
            state.months[0],
        )
    with pytest.raises(ValueError):  # predictor-count contract
        ingest_month(
            state, np.zeros(3), np.zeros((3, 7)), np.ones(3, bool),
            np.datetime64("2999-01-01"),
        )


def test_state_save_load_roundtrip(case, tmp_path):
    *_, state = case
    path = state.save(tmp_path / "serving_state.npz")
    back = ServingState.load(path)
    np.testing.assert_array_equal(back.months, state.months)
    assert back.xvars == state.xvars
    assert (back.window, back.min_periods, back.solver) == (
        state.window, state.min_periods, state.solver
    )
    for name in ("coef", "month_valid", "slopes_bar", "intercept_bar",
                 "x_lo", "x_hi", "gram", "moment", "n_obs", "ysum", "yy"):
        np.testing.assert_allclose(
            getattr(back, name), getattr(state, name),
            rtol=0, atol=0, equal_nan=True,
        )
    # the loaded state serves: one query round-trips a fresh service
    with ERService(back, max_batch=4, max_latency_ms=0.5) as svc:
        value = svc.query(100, np.zeros(back.n_predictors))
    assert isinstance(value, float)  # numerics pinned by the differential tests


def test_pipeline_returns_and_persists_serving_state(tmp_path):
    """Satellite contract: ``run_pipeline`` exposes the fitted serving
    artifacts and persists them next to the report artifacts."""
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.pipeline import run_pipeline

    res = run_pipeline(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=50, n_months=90),
        output_dir=tmp_path,
        make_figure=False,
        compile_pdf=False,
    )
    state = res.serving_state
    assert state is not None
    assert state.n_months == len(res.panel.months)
    assert list(state.xvars)  # figure variables
    assert (tmp_path / "serving_state.npz").exists()
    back = ServingState.load(tmp_path / "serving_state.npz")
    np.testing.assert_allclose(
        back.slopes_bar, state.slopes_bar, equal_nan=True
    )
