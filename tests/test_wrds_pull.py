"""Acquisition layer: SQL builders, universe filter, cache-contract behavior.

Network pulls are not exercised (the ``wrds`` package import is deferred);
cache-hit paths are driven with synthetic parquet files.
"""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.synthetic import SyntheticConfig, generate_synthetic_wrds
from fm_returnprediction_tpu.data.wrds_pull import (
    build_compustat_sql,
    build_crsp_stock_sql,
    build_link_table_sql,
    pull_CRSP_index,
    pull_CRSP_stock,
    subset_to_common_stock_and_exchanges,
)


@pytest.fixture(scope="module")
def wrds():
    return generate_synthetic_wrds(SyntheticConfig(n_firms=30, n_months=24))


def test_universe_filter(wrds):
    out = subset_to_common_stock_and_exchanges(wrds["crsp_m"])
    assert len(out) > 0
    assert (out["securitysubtype"] == "COM").all()
    assert (out["usincflg"] == "Y").all()
    assert out["primaryexch"].isin(["N", "A", "Q"]).all()
    assert out["issuertype"].isin(["ACOR", "CORP"]).all()
    # the synthetic universe deliberately contains excluded rows
    assert len(out) < len(wrds["crsp_m"])


def test_universe_filter_categorical_and_columns(wrds):
    """The categorical fast path (code comparisons) selects the same rows
    as the string path, including a category value absent from the flag's
    dictionary, and ``columns=`` prunes the result."""
    from fm_returnprediction_tpu.data.wrds_pull import FLAG_COLUMNS

    base = wrds["crsp_m"]
    want = subset_to_common_stock_and_exchanges(base)
    cat = base.copy()
    for c in FLAG_COLUMNS:
        cat[c] = cat[c].astype("category")
    got = subset_to_common_stock_and_exchanges(cat)
    assert len(got) == len(want)
    assert (got["permno"].to_numpy() == want["permno"].to_numpy()).all()

    pruned = subset_to_common_stock_and_exchanges(
        cat, columns=["permno", "mthcaldt", "retx"]
    )
    assert list(pruned.columns) == ["permno", "mthcaldt", "retx"]
    assert (pruned["permno"].to_numpy() == want["permno"].to_numpy()).all()

    # a wanted value missing from the category dictionary must not crash
    # (e.g. a universe with no ACOR issuers): drop ACOR from the dictionary
    assert "ACOR" not in cat["issuertype"].cat.categories
    got2 = subset_to_common_stock_and_exchanges(cat)
    assert len(got2) == len(want)


def test_crsp_sql_monthly_vs_daily():
    monthly = build_crsp_stock_sql("M", "1964-01-01", "2013-12-31")
    daily = build_crsp_stock_sql("D", "1964-01-01", "2013-12-31")
    assert "crsp.msf_v2" in monthly and "mthret AS totret" in monthly
    assert "crsp.dsf_v2" in daily and "dlyretx AS retx" in daily
    assert "mthcaldt >= '1964-01-01'" in monthly
    with pytest.raises(ValueError):
        build_crsp_stock_sql("W", "a", "b")


def test_crsp_sql_filter_clause():
    sql = build_crsp_stock_sql("M", "1964-01-01", "2013-12-31", "permno", ["10001", "10002"])
    assert "AND permno IN ('10001', '10002')" in sql


def test_compustat_sql_standard_filters_and_gvkey_column():
    sql = build_compustat_sql("gvkey, datadate", "1964-01-01", "2013-12-31", gvkey="001234")
    for clause in ("indfmt='INDL'", "datafmt='STD'", "popsrc='D'", "consol='C'"):
        assert clause in sql
    # defect SURVEY §2.2.5 fixed: the COLUMN name is interpolated, not the value
    assert "AND gvkey IN ('001234')" in sql


def test_link_table_sql():
    sql = build_link_table_sql()
    assert "substr(linktype,1,1)='L'" in sql
    assert "NOT IN ('LX', 'LD', 'LN')" in sql


def test_cache_hit_returns_filtered_universe(tmp_path, wrds):
    """Defect SURVEY §2.2.7 fixed: a cache hit must return the same filtered
    universe a fresh pull would."""
    raw = wrds["crsp_m"]
    raw.to_parquet(tmp_path / "CRSP_stock_m.parquet", index=False)
    out = pull_CRSP_stock(
        freq="M", data_dir=tmp_path, file_name="CRSP_stock_m.parquet"
    )
    want = subset_to_common_stock_and_exchanges(raw)
    assert len(out) == len(want)
    assert (out["securitysubtype"] == "COM").all()


def test_cache_hit_index_unfiltered(tmp_path, wrds):
    wrds["crsp_index_d"].to_parquet(tmp_path / "CRSP_index_d.parquet", index=False)
    out = pull_CRSP_index(freq="D", data_dir=tmp_path, file_name="CRSP_index_d.parquet")
    assert len(out) == len(wrds["crsp_index_d"])


def test_pipeline_applies_universe_filter(wrds):
    """build_panel must exclude non-common/ADR/non-US rows even when raw
    frames come from an (unfiltered) cache."""
    from fm_returnprediction_tpu.pipeline import build_panel

    panel, _ = build_panel(wrds)
    bad_permnos = set(
        wrds["crsp_m"].loc[wrds["crsp_m"]["usincflg"] != "Y", "permno"]
    )
    assert not bad_permnos.intersection(panel.ids)


def test_wrds_query_retries_then_succeeds(monkeypatch):
    """Transient connection failures retry with a fresh connection; a
    persistent failure surfaces after the attempt budget."""
    import sys
    import types

    from fm_returnprediction_tpu.data import wrds_pull

    calls = {"n": 0}

    class FakeConn:
        def __init__(self, wrds_username=""):
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError(f"drop #{calls['n']}")

        def raw_sql(self, sql, date_cols=None):
            return pd.DataFrame({"x": [1]})

        def close(self):
            pass

    fake = types.ModuleType("wrds")
    fake.Connection = FakeConn
    monkeypatch.setitem(sys.modules, "wrds", fake)

    out = wrds_pull._wrds_query("SELECT 1", "u", [], retries=3, backoff_s=0.0)
    assert calls["n"] == 3 and len(out) == 1

    calls["n"] = -100  # always fails within budget
    class AlwaysFail:
        def __init__(self, wrds_username=""):
            raise ConnectionError("down")

    fake.Connection = AlwaysFail
    with pytest.raises(RuntimeError, match="after 3 attempts"):
        wrds_pull._wrds_query("SELECT 1", "u", [], retries=2, backoff_s=0.0)
