"""Estimator subsystem on the Gram bank (``specgrid.estimators``).

The ISSUE-16 contracts, each differential-pinned against a host oracle:

- FWL partialling-out via the Schur complement on banked per-month Grams
  equals the explicit-controls OLS solve EXACTLY (focal slopes and FM
  means), at the transform level and through the grid engine;
- multi-way absorbed FE via alternating projections on per-month
  sufficient stats matches the dummy-variable within oracle (one-way and
  two-way), with iteration count + convergence disclosed;
- IV/2SLS via two Gram solves matches the closed-form two-stage host
  solve, including the structural-residual R²;
- every pooled sandwich-SE family (iid/white/cluster_month/cluster_firm/
  cluster_twoway) matches the numpy meat-and-bread oracle; the clustered
  FM mean matches ``clustered_mean_se_np``;
- the streaming circular-block bootstrap's draw 0 IS the point estimate,
  chunked accumulation matches one pass, and the Chan sufficient-stats
  merge of disjoint halves is exact;
- the estimator CellSpace dimension is inert for OLS cells (mixed-sweep
  OLS rows bit-match a pure-OLS sweep) and loud everywhere it must be;
- ``grambank.estimator_query`` answers FWL/IV/pooled cells from banked
  stats with ZERO ``(T, N, P)`` panel contractions (ledger-proven) and
  matches the grid route; absorb and firm-clustered pooled SEs are
  rejected loudly (the bank lacks their sufficient stats);
- the ``FMRP_SPECGRID_ESTIMATOR`` knob resolves through
  ``resolve_estimator`` and the reporting parity surfaces reject a
  leaked non-OLS value instead of silently changing the estimand.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.newey_west import (
    clustered_mean_se,
    clustered_mean_se_np,
)
from fm_returnprediction_tpu.specgrid.cellspace import CellSpace
from fm_returnprediction_tpu.specgrid.engine import run_cellspace
from fm_returnprediction_tpu.specgrid.estimators import (
    EST_OLS,
    Estimator,
    StreamingBootstrap,
    parse_estimator,
    resolve_estimator,
    run_estimator_grid_weights,
)
from fm_returnprediction_tpu.specgrid.estimators.absorb import (
    absorb_transform,
    contract_absorb_cells,
)
from fm_returnprediction_tpu.specgrid.estimators.cluster import pooled_fit
from fm_returnprediction_tpu.specgrid.estimators.fwl import fwl_transform
from fm_returnprediction_tpu.specgrid.estimators.iv import iv_r2, iv_transform
from fm_returnprediction_tpu.specgrid.grambank import (
    build_bank,
    estimator_query,
    scenario_query,
)
from fm_returnprediction_tpu.specgrid.grams import contract_spec_grams
from fm_returnprediction_tpu.specgrid.solve import (
    contraction_counts,
    run_spec_grid_weights,
    solve_spec_stats,
)
from fm_returnprediction_tpu.specgrid.specs import Spec, SpecGrid

pytestmark = pytest.mark.estimators

EPS64 = float(jnp.finfo(jnp.float64).eps)


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def panel():
    """(T, N, P=5) panel with NaN holes — transform-level oracle shape."""
    rng = np.random.default_rng(7)
    t, n, p = 18, 60, 5
    y = rng.normal(size=(t, n))
    x = rng.normal(size=(t, n, p))
    x[rng.random((t, n, p)) < 0.03] = np.nan
    y[rng.random((t, n)) < 0.02] = np.nan
    uni = np.ones((1, t, n), bool)
    uidx = jnp.zeros(1, int)
    window = np.ones((1, t), bool)
    return y, x, uni, uidx, window


@pytest.fixture(scope="module")
def grid_panel():
    """Grid-level panel: named columns, one universe, a window spec."""
    rng = np.random.default_rng(11)
    t, n, p = 30, 50, 4
    names = ("a", "b", "c", "z")
    y = rng.normal(size=(t, n))
    x = rng.normal(size=(t, n, p))
    x[rng.random((t, n, p)) < 0.03] = np.nan
    masks = {"all": np.ones((t, n), bool)}
    grid = SpecGrid(specs=(
        Spec("s0", ("a",), "all"),
        Spec("s1", ("a", "b"), "all"),
        Spec("s2", ("a", "b"), "all", window=(5, 25)),
    ), union=names)
    return y, x, masks, grid, names


def _ols_host(yv, xv):
    xa = np.column_stack([np.ones(len(yv)), xv])
    b, *_ = np.linalg.lstsq(xa, yv, rcond=None)
    return b


# ------------------------------------------------------------ spec grammar
def test_parse_grammar_round_trips():
    assert parse_estimator("ols") == EST_OLS
    e = parse_estimator("fwl:c1+c2@iid")
    assert e.kind == "fwl" and e.controls == ("c1", "c2") and e.se == "iid"
    assert e.label == "fwl[c1+c2]"
    e = parse_estimator("absorb:ind+size")
    assert e.kind == "absorb" and e.absorb == ("ind", "size")
    e = parse_estimator("iv:beme~z1+z2")
    assert e.endog == ("beme",) and e.instruments == ("z1", "z2")
    assert e.label == "iv[beme~z1+z2]"
    assert parse_estimator("pooled:cluster_month").se == "cluster_month"
    assert parse_estimator("pooled").se == "iid"


@pytest.mark.parametrize("bad", [
    "fwl",                    # fwl needs controls
    "iv:b",                   # iv needs instruments
    "pooled:cluster_galaxy",  # unknown pooled se family
    "fwl:c@cluster_month",    # pooled-only se on an FM-route kind
    "ridge:0.1",              # unknown kind
])
def test_parse_rejects_bad_grammar(bad):
    with pytest.raises(ValueError):
        parse_estimator(bad)


def test_resolve_env_knob_and_loud_allowed(monkeypatch):
    monkeypatch.delenv("FMRP_SPECGRID_ESTIMATOR", raising=False)
    assert resolve_estimator(None) == EST_OLS
    monkeypatch.setenv("FMRP_SPECGRID_ESTIMATOR", "fwl:beme@iid")
    assert resolve_estimator(None).label == "fwl[beme]"
    # argument beats environment
    assert resolve_estimator("iv:b~z").kind == "iv"
    # the reporting parity surfaces resolve with allowed=("ols",):
    # a leaked non-OLS knob must fail loudly, not change the estimand
    with pytest.raises(ValueError, match="ols"):
        resolve_estimator(None, allowed=("ols",))
    monkeypatch.delenv("FMRP_SPECGRID_ESTIMATOR", raising=False)
    with pytest.raises(TypeError):
        resolve_estimator(123)


def test_reporting_surfaces_reject_leaked_estimator(monkeypatch):
    """The figure sweep resolves the knob with allowed=("ols",) at entry
    — a leaked non-OLS estimator fails loudly before any compute (the
    panel argument is never touched)."""
    monkeypatch.setenv("FMRP_SPECGRID_ESTIMATOR", "pooled:cluster_month")
    from fm_returnprediction_tpu.reporting.figure1 import subset_sweep
    with pytest.raises(ValueError, match="ols"):
        subset_sweep(None, {"All stocks": None}, ["All stocks"])


# ------------------------------------------------- FWL: exact Schur parity
def test_fwl_transform_equals_explicit_controls(panel):
    y, x, uni, uidx, window = panel
    t, _, p = x.shape
    col_full = np.ones((1, p), bool)
    ctrl = np.zeros(p, bool)
    ctrl[3:] = True
    stats = contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col_full), jnp.asarray(window))
    sel_aug = jnp.asarray(
        np.concatenate([[[True]], col_full & ~ctrl], axis=1))
    ctrl_aug = jnp.asarray(np.concatenate([[[True]], ctrl[None]], axis=1))
    full_aug = jnp.asarray(np.concatenate([[[True]], col_full], axis=1))
    st2, deficient = fwl_transform(stats, full_aug, ctrl_aug, EPS64)
    sol = solve_spec_stats(st2, sel_aug)
    beta = np.asarray(sol.beta)[0]
    errs = []
    for m in range(t):
        rows = np.isfinite(y[m]) & np.all(np.isfinite(x[m]), axis=-1)
        if rows.sum() < p + 1:
            continue
        b_full = _ols_host(y[m, rows], x[m, rows])
        errs.append(
            np.abs(beta[m, 1:][~ctrl] - b_full[1:][~ctrl]).max())
    assert errs and max(errs) < 1e-8
    assert not np.asarray(deficient).any()


def test_fwl_grid_vs_explicit_controls(grid_panel):
    y, x, masks, grid, names = grid_panel
    est = Estimator(kind="fwl", controls=("c",))
    res, disc = run_estimator_grid_weights(
        est, y, x, masks, grid, ("reference",))
    r = res["reference"]
    assert disc["kind"] == "fwl" and disc["estimator"] == "fwl[c]"
    grid_ctrl = SpecGrid(specs=(
        Spec("s0", ("a", "c"), "all"),
        Spec("s1", ("a", "b", "c"), "all"),
        Spec("s2", ("a", "b", "c"), "all", window=(5, 25)),
    ), union=names)
    full = run_spec_grid_weights(
        y, x, masks, grid_ctrl, ("reference",), referee=False)["reference"]
    # focal slopes AND the FM tail over them — both exact
    assert np.nanmax(np.abs(r.slopes[:, :, :2] - full.slopes[:, :, :2])) \
        < 1e-10
    assert np.nanmax(np.abs(r.coef[:, :2] - full.coef[:, :2])) < 1e-10


# --------------------------------------------------------- IV: 2SLS parity
def test_iv_vs_closed_form_2sls(panel):
    y, x, uni, uidx, window = panel
    t, _, p = x.shape
    col_iv = np.zeros((1, p), bool)
    col_iv[0, :2] = True                      # structural: 1 + x0 + x1
    inst = np.zeros(p, bool)
    inst[3:] = True                           # excluded instruments: x3, x4
    endog = np.zeros(p, bool)
    endog[1] = True                           # x1 endogenous
    col_eff = col_iv | inst[None]
    stats = contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col_eff), jnp.asarray(window))
    sel_aug = jnp.asarray(np.concatenate([[[True]], col_iv], axis=1))
    z_aug = jnp.asarray(np.concatenate(
        [[[True]], (col_iv & ~endog[None]) | inst[None]], axis=1))
    st_iv, _ = iv_transform(stats, sel_aug, z_aug, EPS64)
    sol = solve_spec_stats(st_iv, sel_aug)
    r2 = np.asarray(iv_r2(sol.beta, stats, sol.month_valid))
    beta = np.asarray(sol.beta)[0]
    errs_b, errs_r2 = [], []
    for m in range(t):
        rows = (np.isfinite(y[m])
                & np.all(np.isfinite(x[m][:, col_eff[0]]), axis=-1))
        if rows.sum() < 5:
            continue
        yv = y[m, rows]
        big_x = np.column_stack([np.ones(rows.sum()), x[m, rows][:, :2]])
        big_z = np.column_stack([np.ones(rows.sum()), x[m, rows][:, 0],
                                 x[m, rows][:, 3:]])
        pz = big_z @ np.linalg.pinv(big_z.T @ big_z) @ big_z.T
        xh = pz @ big_x
        b2sls = np.linalg.pinv(xh.T @ big_x) @ (xh.T @ yv)
        errs_b.append(np.abs(beta[m, :3] - b2sls).max())
        u = yv - big_x @ b2sls
        errs_r2.append(abs(
            float(r2[0, m]) - (1 - (u @ u) / ((yv - yv.mean())**2).sum())))
    assert errs_b and max(errs_b) < 1e-8
    assert max(errs_r2) < 1e-8


# ----------------------------------------- absorbed FE vs dummy-OLS oracle
def test_absorb_oneway_vs_dummy_ols(panel):
    y, x, uni, uidx, window = panel
    t, n, p = x.shape
    rng = np.random.default_rng(70)
    ga = 4
    codes = rng.integers(0, ga, size=(t, n))
    col = np.zeros((1, p), bool)
    col[0, :3] = True
    stats = contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col), jnp.asarray(window))
    sel_aug = jnp.asarray(np.concatenate([[[True]], col], axis=1))
    nc, sc = contract_absorb_cells(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col), jnp.asarray(window), stats.center,
        jnp.asarray(codes, jnp.int32), jnp.zeros((t, n), jnp.int32),
        ga=ga, gb=1)
    st, iters, _ = absorb_transform(stats, sel_aug, nc, sc,
                                    n_fe=1, tol=1e-12, max_iter=50)
    beta = np.asarray(solve_spec_stats(st, sel_aug).beta)[0]
    errs = []
    for m in range(t):
        rows = np.isfinite(y[m]) & np.all(np.isfinite(x[m][:, :3]), axis=-1)
        pres = np.unique(codes[m, rows])
        if rows.sum() < 3 + len(pres):
            continue
        dummies = (codes[m, rows][:, None] == pres[None, :]).astype(float)
        xd = np.column_stack([x[m, rows][:, :3], dummies])
        b, *_ = np.linalg.lstsq(xd, y[m, rows], rcond=None)
        errs.append(np.abs(beta[m, 1:4] - b[:3]).max())
    assert errs and max(errs) < 1e-8
    # one-way absorption is a single exact sweep
    assert int(np.asarray(iters).max()) <= 2


def test_absorb_twoway_vs_dummy_ols(panel):
    y, x, uni, uidx, window = panel
    t, n, p = x.shape
    rng = np.random.default_rng(71)
    ga, gb = 4, 3
    codes_a = rng.integers(0, ga, size=(t, n))
    codes_b = rng.integers(0, gb, size=(t, n))
    col = np.zeros((1, p), bool)
    col[0, :3] = True
    stats = contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col), jnp.asarray(window))
    sel_aug = jnp.asarray(np.concatenate([[[True]], col], axis=1))
    nc, sc = contract_absorb_cells(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col), jnp.asarray(window), stats.center,
        jnp.asarray(codes_a, jnp.int32), jnp.asarray(codes_b, jnp.int32),
        ga=ga, gb=gb)
    st, iters, delta = absorb_transform(stats, sel_aug, nc, sc,
                                        n_fe=2, tol=1e-13, max_iter=200)
    beta = np.asarray(solve_spec_stats(st, sel_aug).beta)[0]
    errs = []
    for m in range(t):
        rows = np.isfinite(y[m]) & np.all(np.isfinite(x[m][:, :3]), axis=-1)
        pa = np.unique(codes_a[m, rows])
        pb = np.unique(codes_b[m, rows])
        if rows.sum() < 3 + len(pa) + len(pb):
            continue
        da = (codes_a[m, rows][:, None] == pa[None, :]).astype(float)
        db = (codes_b[m, rows][:, None] == pb[None, :]).astype(float)
        xd = np.column_stack([x[m, rows][:, :3], da, db[:, 1:]])
        b, *_ = np.linalg.lstsq(xd, y[m, rows], rcond=None)
        errs.append(np.abs(beta[m, 1:4] - b[:3]).max())
    assert errs and max(errs) < 1e-7
    # two-way needs real alternation, and it converged within budget
    assert int(np.asarray(iters).max()) < 200
    assert float(np.asarray(delta).max()) < 1e-10


def test_absorb_grid_disclosure(grid_panel):
    y, x, masks, grid, _ = grid_panel
    rng = np.random.default_rng(72)
    codes = rng.integers(0, 3, size=y.shape)
    res, disc = run_estimator_grid_weights(
        Estimator(kind="absorb", absorb=("ind",)), y, x, masks, grid,
        ("reference",), fe_codes={"ind": codes})
    assert np.asarray(disc["absorb_converged"]).all()
    assert int(np.asarray(disc["absorb_iters"]).max()) >= 1
    assert np.isfinite(res["reference"].coef[1, :2]).all()


# --------------------------------------- pooled sandwich SEs vs numpy oracle
@pytest.mark.parametrize("se_kind", [
    "iid", "white", "cluster_month", "cluster_firm", "cluster_twoway",
])
def test_pooled_sandwich_vs_host_oracle(panel, se_kind):
    y, x, uni, uidx, window = panel
    t, _, p = x.shape
    col = np.zeros((1, p), bool)
    col[0, :3] = True
    stats = contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col), jnp.asarray(window))
    sel_aug = jnp.asarray(np.concatenate([[[True]], col], axis=1))
    rows3 = np.isfinite(y) & np.all(np.isfinite(x[:, :, :3]), axis=-1)
    ys, xs, tids, fids = [], [], [], []
    for m in range(t):
        r = rows3[m]
        ys.append(y[m, r])
        xs.append(x[m, r][:, :3])
        tids.append(np.full(r.sum(), m))
        fids.append(np.flatnonzero(r))
    yv = np.concatenate(ys)
    xa = np.column_stack([np.ones(len(yv)), np.concatenate(xs)])
    tid, fid = np.concatenate(tids), np.concatenate(fids)
    bread = np.linalg.pinv(xa.T @ xa)
    bh = bread @ (xa.T @ yv)
    uh = yv - xa @ bh

    panel_args = (jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
                  jnp.asarray(col), jnp.asarray(window))
    res = pooled_fit(stats, sel_aug, se_kind, EPS64, panel=panel_args)
    assert np.abs(np.asarray(res.beta)[0][:4] - bh).max() < 1e-8

    if se_kind == "iid":
        v = (uh @ uh / (len(yv) - 4)) * bread
    else:
        def meat_by(ids):
            meat = np.zeros((4, 4))
            for g in np.unique(ids):
                s = (xa[ids == g] * uh[ids == g, None]).sum(0)
                meat += np.outer(s, s)
            return meat

        mw = (xa * (uh**2)[:, None]).T @ xa
        meat = {"white": mw,
                "cluster_month": meat_by(tid),
                "cluster_firm": meat_by(fid),
                "cluster_twoway": meat_by(tid) + meat_by(fid) - mw}[se_kind]
        v = bread @ meat @ bread
    assert np.abs(
        np.asarray(res.se)[0][:4] - np.sqrt(np.diag(v))).max() < 1e-8


def test_pooled_month_separable_needs_no_panel(panel):
    """iid/cluster_month are computable from the Grams alone."""
    y, x, uni, uidx, window = panel
    p = x.shape[-1]
    col = np.zeros((1, p), bool)
    col[0, :3] = True
    stats = contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(uni), uidx,
        jnp.asarray(col), jnp.asarray(window))
    sel_aug = jnp.asarray(np.concatenate([[[True]], col], axis=1))
    res = pooled_fit(stats, sel_aug, "cluster_month", EPS64, panel=None)
    assert np.isfinite(np.asarray(res.se)[0][:4]).all()
    with pytest.raises(ValueError, match="panel"):
        pooled_fit(stats, sel_aug, "cluster_firm", EPS64, panel=None)


def test_clustered_mean_se_vs_np_oracle(rng):
    t = 120
    x = rng.standard_normal(t)
    valid = rng.random(t) > 0.1
    clusters = rng.integers(0, 10, size=t)
    se_d = clustered_mean_se(
        jnp.asarray(x), jnp.asarray(valid), jnp.asarray(clusters))
    se_h = clustered_mean_se_np(x[valid], clusters[valid])
    np.testing.assert_allclose(float(se_d), se_h, atol=1e-12)
    # degenerate: one valid entry → NaN, like the NW kernel
    one = np.zeros(t, bool)
    one[3] = True
    assert np.isnan(float(clustered_mean_se(
        jnp.asarray(x), jnp.asarray(one), jnp.asarray(clusters))))


def test_fm_se_families_run_through_grid(grid_panel):
    y, x, masks, grid, _ = grid_panel
    for se in ("iid", "cluster"):
        res, disc = run_estimator_grid_weights(
            Estimator(kind="fwl", controls=("c",), se=se),
            y, x, masks, grid, ("reference",))
        assert disc["se_family"] == se
        assert np.isfinite(res["reference"].nw_se[1, :2]).all()


# --------------------------------------------------- streaming bootstrap
def test_streaming_bootstrap_draw0_chunks_and_merge(grid_panel):
    y, x, masks, grid, _ = grid_panel
    base = run_spec_grid_weights(
        y, x, masks, grid, ("reference",))["reference"]
    k_slopes = base.slopes[:2][:, :, :2]
    args = (k_slopes, base.r2[:2], base.n_obs[:2], base.month_valid[:2])

    sb = StreamingBootstrap(*args, seed=3, chunk=16)
    # draw 0 of the circular block resample IS the identity permutation
    assert np.nanmax(np.abs(sb.point - base.coef[:2, :2])) < 1e-12

    sb.extend(64)
    one = StreamingBootstrap(*args, seed=3, chunk=500)
    one.extend(64)
    assert np.allclose(sb.mean, one.mean, equal_nan=True)
    assert np.allclose(sb.std, one.std, equal_nan=True)

    # Chan merge of disjoint halves == the single pass, exactly
    h1 = StreamingBootstrap(*args, seed=3, chunk=500)
    h1.extend(32)
    h2 = StreamingBootstrap(*args, seed=3, chunk=500)
    h2.draws_done = 32
    h2.extend(64)
    h1.merge(h2)
    assert np.allclose(h1.mean, one.mean, equal_nan=True)
    assert np.allclose(h1.m2, one.m2, equal_nan=True)
    assert one.summary()["draws_done"] == 64


# ------------------------------------------- CellSpace estimator dimension
def _mixed_space():
    return CellSpace(
        regressor_sets=(("m1", ("a",)), ("m2", ("a", "b"))),
        universes=("all", "big"),
        windows=(("full", None), ("early", (0, 15))),
        estimators=(EST_OLS, Estimator(kind="fwl", controls=("c",)),
                    Estimator(kind="absorb", absorb=("ind",)),
                    Estimator(kind="pooled", se="cluster_month")),
    )


def test_cellspace_estimator_dim_decode_and_union():
    space = _mixed_space()
    assert space.union_predictors == ("a", "b", "c")
    for i in range(len(space)):
        assert space.estimators[space.estimator_index(i)] \
            is space.cell(i).estimator
    with pytest.raises(TypeError, match="parse_estimator"):
        CellSpace(regressor_sets=(("m1", ("a",)),), universes=("all",),
                  windows=(("full", None),), estimators=("fwl:c",))


def test_mixed_sweep_ols_cells_match_pure_ols_sweep(rng):
    t, n = 30, 60
    y = rng.normal(size=(t, n))
    x = rng.normal(size=(t, n, 3))
    masks = {"all": np.ones((t, n), bool),
             "big": rng.random((t, n)) > 0.3}
    codes = rng.integers(0, 3, size=(t, n))
    space = _mixed_space()
    frame, _ = run_cellspace(y, x, masks, space, fe_codes={"ind": codes})
    assert {"estimator", "se_family"} <= set(frame.columns)
    ab = frame[frame["estimator"].str.startswith("absorb")]
    assert len(ab) and ab["absorb_converged"].all()

    space_ols = CellSpace(regressor_sets=space.regressor_sets,
                          universes=space.universes, windows=space.windows)
    frame_ols, _ = run_cellspace(y, x[:, :, :2], masks, space_ols)
    key = ["model", "universe", "window", "predictor"]
    got = (frame[frame["estimator"] == "ols"].sort_values(key)
           [["coef", "tstat", "mean_r2"]].to_numpy())
    want = (frame_ols.sort_values(key)
            [["coef", "tstat", "mean_r2"]].to_numpy())
    assert np.allclose(got, want, equal_nan=True)


def test_engine_loud_validations(rng):
    t, n = 12, 20
    y = rng.normal(size=(t, n))
    x = rng.normal(size=(t, n, 1))
    masks = {"all": np.ones((t, n), bool)}
    sets = (("m1", ("a",)),)
    wins = (("full", None),)
    # absorb without fe_codes for the named factor
    with pytest.raises(KeyError, match="ind"):
        run_cellspace(y, x, masks, CellSpace(
            regressor_sets=sets, universes=("all",), windows=wins,
            estimators=(Estimator(kind="absorb", absorb=("ind",)),)))
    # pooled cells cannot ride the slope-series bootstrap re-aggregation
    with pytest.raises(ValueError, match="bootstrap"):
        run_cellspace(y, x, masks, CellSpace(
            regressor_sets=sets, universes=("all",), windows=wins,
            bootstrap=3,
            estimators=(Estimator(kind="pooled", se="iid"),)))


# ------------------------------------- bank-served estimator queries (ZERO
# panel contractions, ledger-proven; acceptance criterion of ISSUE 16)
@pytest.fixture(scope="module")
def bank(grid_panel):
    y, x, masks, _, names = grid_panel
    return build_bank(y, x, masks, CellSpace(
        regressor_sets=(("m2", names),),
        universes=("all",), windows=(("full", None),),
    ))


def test_bank_estimator_query_zero_contractions(bank, grid_panel):
    y, x, masks, _, names = grid_panel
    before = contraction_counts()
    res, disc = estimator_query(bank, "fwl:c")
    assert contraction_counts() == before, \
        "estimator_query touched the (T, N, P) panel"
    assert disc["kind"] == "fwl"
    # parity vs the grid route on the same cell
    grid = SpecGrid(specs=(Spec("m2", names, "all"),), union=names)
    res_g, _ = run_estimator_grid_weights(
        Estimator(kind="fwl", controls=("c",)), y, x, masks, grid,
        ("reference",))
    err = np.nanmax(np.abs(res.coef[0] - res_g["reference"].coef[0]))
    assert err < 1e-12


def test_bank_iv_and_pooled_serve_absorb_rejects(bank):
    res_iv, _ = estimator_query(bank, "iv:b~z")
    assert np.isfinite(res_iv.coef[0, :2]).all()
    res_p, _ = estimator_query(bank, "pooled:cluster_month")
    assert np.isfinite(res_p.coef[0]).all()
    with pytest.raises(ValueError, match="absorb"):
        estimator_query(bank, "absorb:ind")
    # firm clusters need row-level residuals the bank does not hold
    with pytest.raises(ValueError, match="cluster_firm"):
        estimator_query(bank, "pooled:cluster_firm")
    with pytest.raises(KeyError):
        estimator_query(bank, "fwl:not_a_column")


def test_bank_scenario_sweep_estimator_zero_contractions(bank):
    before = contraction_counts()
    df = scenario_query(bank, windows={"full": None, "late": (15, 30)},
                        estimator="fwl:c", bootstrap=3)
    assert contraction_counts() == before
    assert set(df["estimator"]) == {"fwl[c]"}
    assert df["draw"].max() == 2
    # the partialled control never shows up as a reported predictor
    assert not df["predictor"].isin(["c"]).any()


# ------------------------------------------------------- taskgraph knob
def test_taskgraph_knob_carries_estimator(monkeypatch):
    from fm_returnprediction_tpu.taskgraph.tasks import (
        _specgrid_effective_knobs,
    )
    monkeypatch.delenv("FMRP_SPECGRID_ESTIMATOR", raising=False)
    assert _specgrid_effective_knobs(None, None)["estimator"] == "ols@nw"
    assert _specgrid_effective_knobs(
        None, None, "fwl:c@iid")["estimator"] == "fwl[c]@iid"
    monkeypatch.setenv("FMRP_SPECGRID_ESTIMATOR", "pooled:cluster_month")
    assert _specgrid_effective_knobs(
        None, None)["estimator"] == "pooled@cluster_month"
