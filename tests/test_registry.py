"""Registry tests: executable round-trips, corruption, version skew,
artifact plane, warm-pool zero-compile, shared integrity, CLI.

The contracts pinned here are the ISSUE-9 acceptance surface:

- executable serialize → (new-process) deserialize → BIT-IDENTICAL
  outputs, ledger provenance "deserialized";
- corrupt/truncated entry → typed ``CorruptArtifactError`` from the
  verify surface, transparent rebuild (fresh compile) from the fetch
  surface;
- jax-version skew invalidates (never loads a foreign stack's binary);
- ``warm_from_registry`` reaches quoting-ready with zero process-local
  compiles (ledger fresh==0 AND ``fmrp_jit_traces_total`` growth==0),
  differentially pinned bit-identical to the in-process warm-up path;
- the three historical integrity paths (prepared manifest, array-bundle
  checksum, drift array hash) share ONE digest definition.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from fm_returnprediction_tpu.registry import (  # noqa: E402
    CorruptArtifactError,
    Registry,
    array_bundle_digest,
    executable_key,
    load_executable,
    warm_from_registry,
)
from fm_returnprediction_tpu.registry import artifacts as rart  # noqa: E402
from fm_returnprediction_tpu.registry import executables as rexe  # noqa: E402
from fm_returnprediction_tpu.registry.store import (  # noqa: E402
    META_FILE,
    _publish_lock,
    active_registry,
)
from fm_returnprediction_tpu.telemetry import cost_ledger  # noqa: E402
from fm_returnprediction_tpu.telemetry import perf as tperf  # noqa: E402

pytestmark = pytest.mark.registry


@pytest.fixture
def reg_dir(tmp_path, monkeypatch):
    root = tmp_path / "registry"
    monkeypatch.setenv("FMRP_REGISTRY_DIR", str(root))
    return root


def _program():
    return jax.jit(lambda a, b: (a @ b + 1.0).sum(axis=0))


def _args():
    return (jnp.arange(12.0).reshape(3, 4), jnp.ones((4, 2)))


# -- executable plane --------------------------------------------------------


def test_executable_roundtrip_bit_identical(reg_dir):
    a, b = _args()
    fresh = tperf.timed_aot_compile(_program(), a, b, program="reg_rt")
    rec = cost_ledger().records()[-1]
    assert rec.program == "reg_rt"
    assert rec.provenance in ("fresh", "persistent-cache", "uncached")
    want = np.asarray(fresh(a, b))

    fetched = tperf.timed_aot_compile(_program(), a, b, program="reg_rt")
    rec2 = cost_ledger().records()[-1]
    assert rec2.provenance == "deserialized"
    assert rec2.lower_s == 0.0 and rec2.compile_s > 0.0
    # saved_s carries the store-time compile seconds (the bench series)
    assert rec2.saved_s is not None and rec2.saved_s > 0.0
    np.testing.assert_array_equal(np.asarray(fetched(a, b)), want)


def test_new_process_deserialize_bit_identical(reg_dir):
    """The actual cold-start contract: a process that never compiled the
    program loads the entry and reproduces the outputs bit for bit."""
    a, b = _args()
    compiled = tperf.timed_aot_compile(_program(), a, b, program="reg_np")
    want = np.asarray(compiled(a, b))
    signature = tperf.arg_signature((a, b), None)
    out_file = reg_dir.parent / "child_out.npy"
    child = (
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from fm_returnprediction_tpu.registry import load_executable\n"
        f"loaded = load_executable('reg_np', {signature!r})\n"
        "assert loaded is not None, 'registry miss in child'\n"
        "a = jnp.arange(12.0).reshape(3, 4); b = jnp.ones((4, 2))\n"
        f"np.save({str(out_file)!r}, np.asarray(loaded.compiled(a, b)))\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "FMRP_REGISTRY_DIR": str(reg_dir)}
    proc = subprocess.run(
        [sys.executable, "-c", child], env=env, capture_output=True,
        text=True, timeout=240, cwd=str(Path(__file__).parent.parent),
    )
    assert proc.returncode == 0, proc.stderr
    np.testing.assert_array_equal(np.load(out_file), want)


def test_corrupt_entry_typed_error_and_transparent_rebuild(reg_dir):
    a, b = _args()
    tperf.timed_aot_compile(_program(), a, b, program="reg_corrupt")
    reg = Registry(reg_dir)
    key = executable_key(
        "reg_corrupt", tperf.arg_signature((a, b), None)
    )
    entry = reg.executable_dir(key)
    payload = entry / rexe.PAYLOAD_FILE
    blob = bytearray(payload.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    payload.write_bytes(blob)

    # the verify surface reports it as the TYPED error / a corrupt row
    with pytest.raises(CorruptArtifactError):
        reg.verify_entry(entry, deep=True)
    bad = reg.verify(deep=True)
    assert any(key in row["path"] for row in bad)

    # the fetch surface degrades: miss, entry dropped, fresh compile
    assert load_executable(
        "reg_corrupt", tperf.arg_signature((a, b), None)
    ) is None
    assert not (entry / META_FILE).exists()
    rebuilt = tperf.timed_aot_compile(_program(), a, b,
                                      program="reg_corrupt")
    assert cost_ledger().records()[-1].provenance != "deserialized"
    assert np.isfinite(np.asarray(rebuilt(a, b))).all()


def test_truncated_payload_is_a_miss(reg_dir):
    a, b = _args()
    tperf.timed_aot_compile(_program(), a, b, program="reg_trunc")
    reg = Registry(reg_dir)
    entry = reg.executable_dir(
        executable_key("reg_trunc", tperf.arg_signature((a, b), None))
    )
    payload = entry / rexe.PAYLOAD_FILE
    payload.write_bytes(payload.read_bytes()[:16])
    assert load_executable(
        "reg_trunc", tperf.arg_signature((a, b), None)
    ) is None


def test_version_skew_invalidates(reg_dir):
    a, b = _args()
    tperf.timed_aot_compile(_program(), a, b, program="reg_skew")
    reg = Registry(reg_dir)
    entry = reg.executable_dir(
        executable_key("reg_skew", tperf.arg_signature((a, b), None))
    )
    meta = json.loads((entry / META_FILE).read_text())
    meta["jax"] = "0.0.1-other"
    (entry / META_FILE).write_text(json.dumps(meta))
    # manifest still verifies; the ENVIRONMENT check refuses the entry
    assert load_executable(
        "reg_skew", tperf.arg_signature((a, b), None)
    ) is None
    # by DEFAULT gc retains it (skew is judged against this process's
    # stack — a shared registry must survive maintenance from a foreign
    # node); --drop-skewed opts in from the consumers' stack
    assert reg.gc(keep=10) == []
    dropped = reg.gc(keep=10, drop_skewed=True)
    assert any(row["reason"] == "environment skew" for row in dropped)


def test_code_salt_in_key(reg_dir, monkeypatch):
    """A source change (different code salt) must address a DIFFERENT
    entry — an old executable can never answer for new code."""
    key_now = executable_key("p", "sig")
    monkeypatch.setattr(rexe, "_SALT", "something-else")
    assert executable_key("p", "sig") != key_now


def test_cpu_custom_call_program_not_stored(reg_dir):
    """XLA CPU lowers linalg (eigh/qr/svd — LAPACK) to custom calls whose
    serialized executables embed raw host function POINTERS: a consumer
    process calling one segfaults. The store path must skip such programs
    (they ride the persistent XLA cache instead), disclosed in
    ``fmrp_registry_store_skipped_total``."""
    if jax.devices()[0].platform != "cpu":
        pytest.skip("CPU custom-call hazard is a CPU-backend property")
    from fm_returnprediction_tpu.telemetry import metrics as tmetrics

    a = jnp.eye(4)
    prog = jax.jit(lambda g: jnp.linalg.eigh(g)[0])
    compiled = tperf.timed_aot_compile(prog, a, program="reg_eigh")
    assert np.isfinite(np.asarray(compiled(a))).all()
    # nothing was stored: the entry is absent and the skip is counted
    assert load_executable(
        "reg_eigh", tperf.arg_signature((a,), None)
    ) is None
    skipped = tmetrics.registry().collect().get(
        "fmrp_registry_store_skipped_total", {}
    )
    assert any(
        dict(key).get("program") == "reg_eigh" for key in skipped
    )


def test_registry_off_is_passthrough(monkeypatch):
    monkeypatch.delenv("FMRP_REGISTRY_DIR", raising=False)
    assert active_registry() is None
    a, b = _args()
    compiled = tperf.timed_aot_compile(_program(), a, b, program="reg_off")
    assert cost_ledger().records()[-1].provenance != "deserialized"
    assert np.isfinite(np.asarray(compiled(a, b))).all()


# -- artifact plane ----------------------------------------------------------


def test_artifact_roundtrip_and_corruption(reg_dir, tmp_path):
    src = tmp_path / "payload.csv"
    src.write_text("a,b\n1,2\n")
    entry = rart.put_files("frames", "fp1", [src])
    assert entry is not None
    got = rart.get_file("frames", "payload.csv", "fp1", deep=True)
    assert got is not None and got.read_text() == src.read_text()

    # latest-entry resolution: a second fingerprint wins the default
    src.write_text("a,b\n3,4\n")
    rart.put_files("frames", "fp2", [src])
    assert rart.get_entry_dir("frames").name == "fp2"

    # corrupt the payload: deep get raises the TYPED error
    (entry / "payload.csv").write_text("a,b\n9,9\n")
    with pytest.raises(CorruptArtifactError):
        rart.get_file("frames", "payload.csv", "fp1", deep=True)


def test_serving_state_artifact_roundtrip(reg_dir, small_state):
    rart.put_serving_state(small_state, "fpX")
    loaded = rart.load_serving_state("fpX")
    assert loaded is not None
    np.testing.assert_array_equal(loaded.slopes_bar, small_state.slopes_bar)
    np.testing.assert_array_equal(loaded.coef, small_state.coef)
    assert loaded.xvars == small_state.xvars


# -- warm pool ---------------------------------------------------------------


@pytest.fixture(scope="module")
def small_state():
    from fm_returnprediction_tpu.serving.state import build_serving_state

    rng = np.random.default_rng(7)
    t, n, p = 30, 24, 3
    y = rng.standard_normal((t, n)).astype(np.float32)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    mask = np.ones((t, n), bool)
    return build_serving_state(y, x, mask, window=12, min_periods=6)


def test_warm_from_registry_zero_compile_and_bit_identical(
    reg_dir, small_state, monkeypatch
):
    from fm_returnprediction_tpu.serving.service import ERService

    rart.put_serving_state(small_state, "fpW")
    # populate the executable plane the way a publisher does
    svc0, _ = warm_from_registry(state=small_state, max_batch=32)
    svc0.close()

    # the fresh-replica path: state AND executables resolve from the
    # registry; nothing may trace or compile (record_trace counters)
    svc, report = warm_from_registry(max_batch=32, strict=True)
    try:
        assert report.zero_compile
        assert report.fresh_compiles == 0
        assert report.trace_growth == 0
        assert report.deserialized == len(svc.executor.buckets())
        assert all(p.endswith("@deserialized") for p in report.programs)
        assert report.saved_s > 0.0

        # differential pin: bit-identical to the in-process warm-up path
        m = int(np.nonzero(small_state.have_coef())[0][-1])
        xs = np.linspace(-1.0, 1.0, small_state.n_predictors * 5).reshape(
            5, small_state.n_predictors
        ).astype(small_state.dtype)
        got = svc.query_many([m] * 5, xs)
    finally:
        svc.close()
    monkeypatch.delenv("FMRP_REGISTRY_DIR", raising=False)
    with ERService(small_state, max_batch=32) as ref:
        want = ref.query_many([m] * 5, xs)
    np.testing.assert_array_equal(got, want)
    assert np.isfinite(want).all()


def test_warm_from_registry_strict_raises_on_empty_registry(
    reg_dir, small_state
):
    with pytest.raises(RuntimeError, match="not compile-free"):
        warm_from_registry(state=small_state, max_batch=4, strict=True)


def test_warm_from_registry_partial_miss_degrades(reg_dir, small_state):
    """A partial registry is a legitimate degraded start: misses compile
    fresh (and are stored), the report discloses them."""
    svc, report = warm_from_registry(state=small_state, max_batch=8)
    svc.close()
    assert report.fresh_compiles == len(report.programs) > 0
    svc2, report2 = warm_from_registry(state=small_state, max_batch=8,
                                       strict=True)
    svc2.close()
    assert report2.zero_compile


# -- shared integrity --------------------------------------------------------


def test_one_digest_definition_across_paths(tmp_path):
    """Bundle checksum, drift array hash, and the registry digest are ONE
    definition — a manifest written before the dedup compares equal."""
    from fm_returnprediction_tpu.guard.drift import summarize_arrays
    from fm_returnprediction_tpu.utils.cache import (
        load_array_bundle,
        save_array_bundle,
    )

    arrays = {
        "a": np.arange(6.0).reshape(2, 3),
        "b": np.array([True, False]),
    }
    digest = array_bundle_digest(arrays)
    # the drift sentinel's array-artifact identity hash
    assert summarize_arrays(arrays)["sha256"] == digest
    # the bundle embeds and verifies the same digest
    path = save_array_bundle(tmp_path / "bundle.npz", arrays)
    loaded, _ = load_array_bundle(path)
    assert array_bundle_digest(loaded) == digest
    # the frozen historical definition, byte for byte
    import hashlib

    h = hashlib.sha256()
    for name in sorted(arrays):
        arr = np.ascontiguousarray(arrays[name])
        h.update(f"{name}|{arr.dtype.str}|{arr.shape}|".encode())
        h.update(arr.data)
    assert digest == h.hexdigest()


def test_prepared_candidates_route_through_registry(tmp_path, monkeypatch):
    from fm_returnprediction_tpu.data.prepared import prepared_candidates

    monkeypatch.delenv("FMRP_REGISTRY_DIR", raising=False)
    raw = tmp_path / "raw"
    assert prepared_candidates(raw) == [raw / "_prepared"]

    monkeypatch.setenv("FMRP_REGISTRY_DIR", str(tmp_path / "reg"))
    cands = prepared_candidates(raw)
    assert len(cands) == 2
    assert str(cands[0]).startswith(str(tmp_path / "reg"))
    assert cands[1] == raw / "_prepared"  # legacy read fallback stays
    # distinct raw dirs get distinct registry slots
    other = prepared_candidates(tmp_path / "raw2")
    assert other[0] != cands[0]


def test_prepared_slots_visible_to_maintenance(reg_dir):
    """Prepared checkpoint slots — the tree's largest payloads — must be
    covered by ls/verify/gc, not just the executable/artifact planes."""
    from fm_returnprediction_tpu.registry.integrity import manifest_entry

    slot = Registry(reg_dir).prepared_root("slot01")
    slot.mkdir(parents=True)
    payload = slot / "base.values.npy"
    payload.write_bytes(b"\x93NUMPY-fake-payload")
    (slot / "meta.json").write_text(json.dumps({
        "fingerprint": "f", "version": 3,
        "manifest": {"base.values.npy": manifest_entry(payload)},
    }))
    reg = Registry(reg_dir)
    rows = [r for r in reg.ls() if r["kind"] == "prepared"]
    assert len(rows) == 1 and rows[0]["bytes"] == payload.stat().st_size
    assert reg.verify(deep=True) == []
    # readable slots survive gc (they self-overwrite in place)
    assert reg.gc(keep=1) == []
    assert (slot / "meta.json").exists()
    # corruption is flagged; a torn slot (no meta) is collected
    payload.write_bytes(b"different-bytes-same-len")
    assert any("base.values.npy" in r["error"] for r in reg.verify(deep=True))
    (slot / "meta.json").unlink()
    dropped = reg.gc(keep=1)
    assert any(r["reason"] == "torn prepared slot" for r in dropped)
    assert not slot.exists()


def test_gc_keeps_complete_signature_sets(reg_dir):
    """gc groups executables per (program, signature): a complete live
    bucket set — many signatures of one program — is never thinned by
    the default retention."""
    for k in (2, 3, 5):
        tperf.timed_aot_compile(
            jax.jit(lambda x, y: (x @ y).sum()),
            jnp.ones((k, 4)), jnp.ones((4, 2)),
            program="reg_buckets",
        )
    reg = Registry(reg_dir)
    assert reg.gc(keep=1) == []  # three signatures, three groups
    assert sum(1 for r in reg.ls() if r.get("program") == "reg_buckets") == 3


def test_serve_state_task_stale_until_registry_published(
    reg_dir, tmp_path, small_state, monkeypatch
):
    """--registry-dir on an up-to-date DAG must not silently no-op: the
    serve_state task reads as STALE while the armed registry lacks this
    panel's serving-state entry, and current again once published."""
    from fm_returnprediction_tpu.registry.integrity import file_sha256
    from fm_returnprediction_tpu.taskgraph.tasks import (
        PANEL_FILE,
        _serve_state_registry_current,
    )

    processed = tmp_path / "processed"
    processed.mkdir()
    panel = processed / PANEL_FILE
    panel.write_bytes(b"panel-checkpoint-bytes")

    monkeypatch.delenv("FMRP_REGISTRY_DIR", raising=False)
    assert _serve_state_registry_current(processed)  # registry off: no opinion
    monkeypatch.setenv("FMRP_REGISTRY_DIR", str(reg_dir))
    assert not _serve_state_registry_current(processed)  # armed, empty: stale
    rart.put_serving_state(small_state, file_sha256(panel)[:32])
    assert _serve_state_registry_current(processed)  # published: current


# -- maintenance CLI ---------------------------------------------------------


def test_cli_ls_verify_gc(reg_dir, capsys):
    from fm_returnprediction_tpu.registry.__main__ import main

    a, b = _args()
    tperf.timed_aot_compile(_program(), a, b, program="reg_cli")
    assert main(["--registry-dir", str(reg_dir), "ls"]) == 0
    assert "reg_cli" in capsys.readouterr().out

    assert main(["--registry-dir", str(reg_dir), "verify"]) == 0

    # corrupt → verify exits 1 and names the entry
    reg = Registry(reg_dir)
    entry = reg.executable_dir(
        executable_key("reg_cli", tperf.arg_signature((a, b), None))
    )
    payload = entry / rexe.PAYLOAD_FILE
    payload.write_bytes(payload.read_bytes()[:-4] + b"XXXX")
    assert main(["--registry-dir", str(reg_dir), "verify"]) == 1

    # gc --dry-run reports, gc drops (keep=0 clears everything)
    assert main(["--registry-dir", str(reg_dir), "gc", "--keep", "0",
                 "--dry-run"]) == 0
    assert (entry / META_FILE).exists()
    assert main(["--registry-dir", str(reg_dir), "gc", "--keep", "0"]) == 0
    assert not (entry / META_FILE).exists()


def test_cli_no_root_exits_2(monkeypatch, capsys):
    from fm_returnprediction_tpu.registry.__main__ import main

    monkeypatch.delenv("FMRP_REGISTRY_DIR", raising=False)
    assert main(["ls"]) == 2


# -- concurrent publishers (the ISSUE-13 advisory publish lock) ---------------


_RACE_PUBLISHER = """
import sys
from fm_returnprediction_tpu.registry.store import Registry

root, writer, rounds = sys.argv[1], sys.argv[2], int(sys.argv[3])
reg = Registry(root)
entry = reg.artifacts_root / "raced" / "fp"
for k in range(rounds):
    token = f"{writer}:{k}".encode() * 2048  # big enough to lose a race mid-write
    reg.write_entry(
        entry,
        {"a.bin": token, "b.bin": token},
        {"kind": "race", "writer": writer, "round": k},
    )
print("RACE_DONE", writer)
"""


def test_racing_publishers_never_expose_a_torn_entry(tmp_path):
    """N PROCESSES publishing the same entry concurrently (the
    multi-process fleet/spec-grid warm scenario): the advisory
    ``.publish.lock`` serializes the per-file rename windows, so a
    reader polling throughout must only ever observe an ABSENT entry
    (meta invalidated mid-publish) or a COHERENT one — manifest deep-
    verifies AND both payloads carry the same writer's token. Without
    the flock, file A from one writer lands under file B + manifest of
    the other (caught here as a verify failure or token mismatch).

    The polling reader holds the SAME advisory lock per observation: a
    lockless reader re-reading an entry that is being re-published can
    still pair round k's meta with round k+1's payload (the runtime
    consumers catch that as a typed CorruptArtifactError and degrade to
    a fresh compile — disclosed); the lock is the writers' interleaving
    fence plus the coherent-snapshot primitive for readers that want
    one."""
    import time as _time

    from fm_returnprediction_tpu.registry import integrity

    root = tmp_path / "registry"
    rounds = 20
    env = {**os.environ, "PYTHONPATH": str(Path(__file__).parent.parent)}
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RACE_PUBLISHER, str(root), w,
             str(rounds)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for w in ("alpha", "beta")
    ]
    reg = Registry(root)
    entry = reg.artifacts_root / "raced" / "fp"
    observations = 0
    deadline = _time.monotonic() + 120
    try:
        while any(p.poll() is None for p in procs):
            assert _time.monotonic() < deadline, "racing publishers hung"
            entry.mkdir(parents=True, exist_ok=True)
            with _publish_lock(entry):
                meta = reg.read_meta(entry)
                if meta is None:
                    continue  # mid-publish: absent is the DISCLOSED state
                try:
                    reg.verify_entry(entry, deep=True)
                except integrity.CorruptArtifactError as exc:
                    # under the lock no publish is in flight: ANY
                    # mismatch is the torn entry the lock must prevent
                    pytest.fail(f"reader observed a torn entry: {exc}")
                a = (entry / "a.bin").read_bytes()
                b = (entry / "b.bin").read_bytes()
                assert a == b, (
                    "payloads from two different writers interleaved"
                )
            observations += 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p in procs:
        out, _ = p.communicate(timeout=30)
        assert p.returncode == 0, out
        assert "RACE_DONE" in out
    # the final published entry must be whole and single-writer
    meta = reg.verify_entry(entry, deep=True)
    assert meta["kind"] == "race"
    assert (entry / "a.bin").read_bytes() == (entry / "b.bin").read_bytes()
    assert observations >= 0  # polling is best-effort; the asserts above bite
