"""Worker for the host-exchange collective tests (test_multiprocess.py).

Each spawned process joins the ``FMRP_DIST_*`` bootstrap and exercises
every exchange primitive the platform builds on — allgather (rank
ordering), sum_tree (the psum drop-in: identical merged leaves on every
rank), broadcast, barrier — plus the telemetry identity the bootstrap
stamps (``process_index`` label on the Prometheus export).

Usage: python mp_exchange_worker.py <pid> <nprocs> <port>
"""

import os
import sys

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["FMRP_DIST_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["FMRP_DIST_PROCS"] = str(nprocs)
os.environ["FMRP_DIST_PROC_ID"] = str(pid)
os.environ["FMRP_DIST_JAX"] = "0"

import numpy as np  # noqa: E402

from fm_returnprediction_tpu.parallel import distributed as dist  # noqa: E402

assert dist.initialize_distributed() == (pid, nprocs)
ex = dist.host_exchange()
assert ex is not None and dist.dist_active()

# allgather: every rank sees every contribution, rank-ordered
vals = ex.allgather_obj(pid * 10)
assert vals == [r * 10 for r in range(nprocs)], vals

# sum_tree: the host-merge drop-in for psum over additive stats — every
# rank computes the identical rank-ordered fold
tree = {"gram": np.full((2, 3), float(pid + 1)), "n": np.array([pid])}
merged = ex.sum_tree(tree)
want_gram = sum(r + 1.0 for r in range(nprocs))
assert np.array_equal(merged["gram"], np.full((2, 3), want_gram))
assert merged["n"][0] == sum(range(nprocs))

# broadcast: non-root contributions are ignored
got = ex.broadcast_obj("root-truth" if pid == 0 else f"noise-{pid}")
assert got == "root-truth", got

# barrier with an agreed tag passes; the transport counters moved
ex.barrier("checkpoint")
assert ex._m_rounds.value >= 4

# the bootstrap stamped the telemetry identity: every exported series
# carries process_index="<rank>" (merged scrapes stay attributable)
from fm_returnprediction_tpu import telemetry  # noqa: E402
from fm_returnprediction_tpu.telemetry import identity  # noqa: E402

assert identity.process_index() == pid
text = telemetry.registry().to_prometheus()
assert f'process_index="{pid}"' in text, text[:400]

print(f"EX_OK {pid}", flush=True)
