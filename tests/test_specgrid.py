"""Spec-grid subsystem: Gram-route differentials, additivity, scenarios.

The contract under test (ISSUE 3 acceptance): the Gram-contracted grid
solve must be numerically equal (≤1e-6; observed ~1e-14 at f64) to the
per-cell batched-QR route on synthetic panels — including masked/thin
months — with rank-deficient cells falling back to the QR referee; and the
Gram contraction must be additive over firm shards (the property that
makes the chunked accumulation and any future multi-chip psum exact).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
from fm_returnprediction_tpu.specgrid import (
    Spec,
    SpecGrid,
    contract_spec_grams,
    program_trace_counts,
    run_spec_grid,
    subperiod_windows,
    table2_grid,
    winsor_variant,
)

pytestmark = pytest.mark.specgrid


def _panel(rng, t=48, n=90, p=6, nan_frac=0.05):
    x = rng.standard_normal((t, n, p))
    beta = rng.standard_normal(p) * 0.1
    y = x @ beta + 0.2 * rng.standard_normal((t, n))
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan)
    x[rng.random((t, n, p)) < nan_frac] = np.nan
    size = rng.random(n)
    masks = {
        "All": mask,
        "Big": mask & (size > 0.4)[None, :],
        "Huge": mask & (size > 0.7)[None, :],
    }
    return y, x, masks


def _nested_grid(p_sizes=(3, 6), universes=("All", "Big", "Huge"), **kw):
    names = [f"x{i}" for i in range(max(p_sizes))]
    specs = tuple(
        Spec(f"m{k} | {u}", tuple(names[:k]), u)
        for k in p_sizes for u in universes
    )
    return SpecGrid(specs, **kw)


def _percell_reference(y, x, masks, grid):
    """The incumbent route: one batched-QR ``fama_macbeth`` per cell."""
    out = []
    t = y.shape[0]
    for spec in grid.specs:
        pos = grid.column_positions(spec)
        w = np.ones(t, bool)
        if spec.window is not None:
            w[:] = False
            w[spec.window[0]:spec.window[1]] = True
        mask = jnp.asarray(masks[spec.universe] & w[:, None])
        cs, fm = jax.device_get(
            fama_macbeth(
                jnp.asarray(y), jnp.asarray(x[:, :, pos]), mask,
                nw_lags=grid.nw_lags, min_months=grid.min_months,
                weight=grid.weight, solver="qr",
            )
        )
        out.append((cs, fm))
    return out


def _assert_close(a, b, atol=1e-6, msg=""):
    a, b = np.asarray(a, float), np.asarray(b, float)
    both_nan = np.isnan(a) & np.isnan(b)
    np.testing.assert_allclose(
        np.where(both_nan, 0.0, a), np.where(both_nan, 0.0, b),
        rtol=1e-6, atol=atol, err_msg=msg,
    )


def test_grid_matches_percell_qr_route():
    """Every (model, universe) cell from the fused Gram program equals the
    per-cell QR route to well inside 1e-6 — coef, t-stat, NW SE, monthly
    slopes, R², and the month gate — including thin months."""
    rng = np.random.default_rng(7)
    y, x, masks = _panel(rng)
    # thin months: barely enough complete-case rows for the wide model
    for m, extra in ((0, 1), (1, 2), (2, 3)):
        keep = np.zeros(y.shape[1], bool)
        keep[: 7 + extra] = True
        y[m, ~keep] = np.nan
    grid = _nested_grid()
    res = run_spec_grid(y, x, masks, grid)
    for s, (cs, fm) in enumerate(_percell_reference(y, x, masks, grid)):
        pos = grid.column_positions(grid.specs[s])
        name = grid.specs[s].name
        _assert_close(res.coef[s, pos], fm.coef, msg=f"{name} coef")
        _assert_close(res.tstat[s, pos], fm.tstat, msg=f"{name} tstat")
        _assert_close(res.nw_se[s, pos], fm.nw_se, msg=f"{name} nw_se")
        _assert_close(res.mean_r2[s], fm.mean_r2, msg=f"{name} r2")
        _assert_close(res.mean_n[s], fm.mean_n, msg=f"{name} n")
        assert res.n_months[s] == fm.n_months, name
        _assert_close(res.slopes[s][:, pos], cs.slopes, msg=f"{name} slopes")
        # intercepts pin the centered-basis shift recovery (a = a_c − b·c)
        _assert_close(res.intercept[s], cs.intercept, msg=f"{name} intercept")
        _assert_close(res.r2[s], cs.r2, msg=f"{name} r2 series")
        np.testing.assert_array_equal(
            res.month_valid[s], cs.month_valid, err_msg=name
        )


def test_rank_deficient_cell_falls_back_to_referee():
    """A collinear predictor pair makes every month of the affected cells
    rank-deficient at the pinv cutoff: those specs must be flagged and
    re-solved by the QR referee, landing EXACTLY on the per-cell route;
    clean specs must not pay the fallback."""
    rng = np.random.default_rng(11)
    y, x, masks = _panel(rng, p=5, nan_frac=0.0)
    x[:, :, 4] = -1.5 * x[:, :, 3]  # exact collinearity
    names = [f"x{i}" for i in range(5)]
    grid = SpecGrid((
        Spec("clean | All", tuple(names[:3]), "All"),
        Spec("collinear | All", tuple(names), "All"),
        Spec("collinear | Big", tuple(names), "Big"),
    ))
    res = run_spec_grid(y, x, masks, grid)
    assert res.referee_specs == (1, 2)
    assert res.suspect_months[0] == 0
    assert (res.suspect_months[1:] > 0).all()
    for s, (cs, fm) in enumerate(_percell_reference(y, x, masks, grid)):
        pos = grid.column_positions(grid.specs[s])
        name = grid.specs[s].name
        # referee'd cells are the SAME computation — exact equality
        if s in res.referee_specs:
            np.testing.assert_array_equal(
                res.coef[s, pos], fm.coef, err_msg=name
            )
            np.testing.assert_array_equal(
                res.slopes[s][:, pos], cs.slopes, err_msg=name
            )
        else:
            _assert_close(res.coef[s, pos], fm.coef, msg=name)


def test_window_restricts_the_sample():
    """A windowed spec equals the per-cell run on the window-ANDed mask,
    and months outside the window never run."""
    rng = np.random.default_rng(13)
    y, x, masks = _panel(rng, t=40, p=3)
    names = ["x0", "x1", "x2"]
    grid = SpecGrid((
        Spec("full", tuple(names), "All"),
        Spec("late", tuple(names), "All", window=(20, 40)),
    ))
    res = run_spec_grid(y, x, masks, grid)
    assert not res.month_valid[1, :20].any()
    assert res.n_months[1] < res.n_months[0]
    _, fm = _percell_reference(y, x, masks, grid)[1]
    pos = grid.column_positions(grid.specs[1])
    _assert_close(res.coef[1, pos], fm.coef)
    _assert_close(res.tstat[1, pos], fm.tstat)


def test_gram_contraction_additive_over_firm_shards():
    """Contracting two disjoint firm shards and summing the stats equals
    contracting the full panel — the additivity the chunked accumulation
    and the sharded FM path rely on — and the result is firm-chunk
    invariant."""
    rng = np.random.default_rng(17)
    y, x, masks = _panel(rng, t=24, n=64, p=4)
    grid = _nested_grid(p_sizes=(2, 4))
    names = list(masks)
    uni = jnp.stack([jnp.asarray(masks[n]) for n in names])
    uidx = jnp.asarray(grid.universe_index(names))
    col_sel = jnp.asarray(grid.column_selector())
    window = jnp.asarray(grid.window_masks(y.shape[0]))

    full = jax.device_get(contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), uni, uidx, col_sel, window
    ))
    # shards must share ONE center (any fixed shift is algebraically
    # valid); per-shard recomputed centers would break additivity
    center = jnp.asarray(full.center)
    half = 64 // 2
    parts = [
        jax.device_get(contract_spec_grams(
            jnp.asarray(y[:, sl]), jnp.asarray(x[:, sl]), uni[:, :, sl],
            uidx, col_sel, window, center=center,
        ))
        for sl in (slice(0, half), slice(half, None))
    ]
    additive = ("gram", "moment", "n", "ysum", "yy")
    for name in additive:
        np.testing.assert_allclose(
            getattr(full, name),
            getattr(parts[0], name) + getattr(parts[1], name),
            rtol=1e-12, atol=1e-12, err_msg=name,
        )

    chunked = jax.device_get(contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), uni, uidx, col_sel, window,
        firm_chunk=17,
    ))
    for name in additive + ("center",):
        np.testing.assert_allclose(getattr(full, name),
                                   getattr(chunked, name),
                                   rtol=1e-12, atol=1e-12, err_msg=name)


def test_grid_is_one_fused_program():
    """A clean grid run costs exactly one specgrid program trace and zero
    referee dispatches; a repeat run at the same shapes costs zero."""
    rng = np.random.default_rng(19)
    y, x, masks = _panel(rng, t=30, n=70, p=4, nan_frac=0.0)
    grid = _nested_grid(p_sizes=(2, 4), universes=("All", "Big"))
    before = program_trace_counts()
    res = run_spec_grid(y, x, masks, grid)
    mid = program_trace_counts()
    run_spec_grid(y, x, masks, grid)
    after = program_trace_counts()
    assert res.referee_specs == ()
    assert (mid.get("specgrid_program", 0)
            - before.get("specgrid_program", 0)) == 1
    assert (after.get("specgrid_referee_calls", 0)
            == mid.get("specgrid_referee_calls", 0))
    assert after["specgrid_program"] == mid["specgrid_program"]


def _formatted_frames_close(a: pd.DataFrame, b: pd.DataFrame,
                            tol: float = 1.5e-3) -> None:
    """Layout-identical and cell-wise equal up to ONE final-digit rounding
    step: a raw-value difference of ~1e-9 can still flip a ``%.3f`` cell
    sitting on a 0.0005 boundary, so exact string equality is too strong a
    contract for cross-route comparison (the raw-value 1e-6 differential
    in ``test_grid_matches_percell_qr_route`` is the real one)."""
    assert a.index.equals(b.index)
    assert a.columns.equals(b.columns)
    for col in a.columns:
        for idx in a.index:
            va, vb = a.loc[idx, col], b.loc[idx, col]
            if va == vb:
                continue
            assert va != "" and vb != "", (idx, col, va, vb)
            fa = float(str(va).replace(",", ""))
            fb = float(str(vb).replace(",", ""))
            assert abs(fa - fb) <= tol, (idx, col, va, vb)


def test_build_table_2_gram_equals_stacked_route(monkeypatch):
    """The rewired Table 2: the Gram route's formatted frame matches the
    pre-existing stacked/fusion route's cell for cell (up to a final-digit
    rounding flip on exact ``%.3f`` boundaries; referee'd thin cells are
    exact)."""
    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.panel.characteristics import get_factors
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.panel.transform_compustat import (
        add_report_date,
        calc_book_equity,
        expand_compustat_annual_to_monthly,
        merge_CRSP_and_Compustat,
    )
    from fm_returnprediction_tpu.panel.transform_crsp import (
        calculate_market_equity,
    )
    from fm_returnprediction_tpu.reporting.figure1 import subset_sweep
    from fm_returnprediction_tpu.reporting.table2 import build_table_2

    wrds = generate_synthetic_wrds(SyntheticConfig(n_firms=35, n_months=72))
    crsp = calculate_market_equity(wrds["crsp_m"])
    comp = expand_compustat_annual_to_monthly(
        calc_book_equity(add_report_date(wrds["comp"].copy()))
    )
    merged = merge_CRSP_and_Compustat(crsp, comp, wrds["ccm"])
    merged["mthcaldt"] = merged["jdate"]
    panel, factors = get_factors(
        merged, wrds["crsp_d"], wrds["crsp_index_d"]
    )
    masks = compute_subset_masks(panel)

    gram_t2 = build_table_2(panel, masks, factors, route="gram")
    stacked_t2 = build_table_2(panel, masks, factors, route="stacked")
    _formatted_frames_close(gram_t2, stacked_t2)

    # the figure/decile sweep: per-month cross-sections agree across routes
    gram_sweep = subset_sweep(panel, masks, list(masks), route="gram")
    stacked_sweep = subset_sweep(panel, masks, list(masks), route="stacked")
    assert list(gram_sweep) == list(stacked_sweep)
    for name in gram_sweep:
        g, s = gram_sweep[name], stacked_sweep[name]
        _assert_close(g.cs.slopes, s.cs.slopes, msg=f"{name} slopes")
        _assert_close(g.cs.r2, s.cs.r2, msg=f"{name} r2")
        np.testing.assert_array_equal(g.cs.month_valid, s.cs.month_valid)
        _assert_close(g.rolled, s.rolled, msg=f"{name} rolled")
        _assert_close(g.deciles.mean_returns, s.deciles.mean_returns,
                      atol=1e-8, msg=f"{name} deciles")
        _assert_close(g.deciles.spread, s.deciles.spread,
                      atol=1e-8, msg=f"{name} spread")
        assert g.decile_params == s.decile_params

    # env resolution: the flag routes the default path
    monkeypatch.setenv("FMRP_SPECGRID_ROUTE", "stacked")
    env_t2 = build_table_2(panel, masks, factors)
    pd.testing.assert_frame_equal(env_t2, stacked_t2)


def test_table2_grid_preset_orders_cells_model_major():
    from fm_returnprediction_tpu.models.lewellen import MODELS
    from fm_returnprediction_tpu.panel.subsets import SUBSET_ORDER

    variables = {label: f"c{i}" for i, label in enumerate(
        {p for m in MODELS for p in m.predictors}
    )}
    grid = table2_grid(variables)
    assert len(grid) == len(MODELS) * len(SUBSET_ORDER)
    s = grid.specs[1 * len(SUBSET_ORDER) + 2]  # model 2, subset 3
    assert s.universe == SUBSET_ORDER[2]
    assert len(s.predictors) == len(MODELS[1].predictors)
    # union keeps first-seen (model-major) order and covers every model
    assert len(grid.union_predictors) == len(MODELS[2].predictors)


def test_scenarios_frame_shape_and_subperiods():
    """The scenario sweep emits one tidy row per (spec, predictor), the
    subperiod cells see fewer months than the full-sample cells, and the
    winsor/weight dimensions land as columns."""
    rng = np.random.default_rng(23)

    class _MiniPanel:
        """Duck-typed stand-in: var/select/mask/months on raw arrays."""

        def __init__(self, y, x, mask, names):
            self._y, self._x, self.mask = y, x, mask
            self._names = names
            self.months = np.arange(y.shape[0])

        def var(self, name):
            assert name == "retx"
            return self._y

        def select(self, cols):
            idx = [self._names.index(c) for c in cols]
            return self._x[:, :, idx]

    y, x, masks = _panel(rng, t=36, n=60, p=3)
    names = ["c0", "c1", "c2"]
    panel = _MiniPanel(y, x, masks["All"], names)
    variables = {"V0": "c0", "V1": "c1", "V2": "c2"}

    import dataclasses

    from fm_returnprediction_tpu.models.lewellen import ModelSpec
    from fm_returnprediction_tpu.specgrid import run_scenarios

    models = [ModelSpec("Model A", ["V0", "V1"]),
              ModelSpec("Model B", ["V0", "V1", "V2"])]
    frame = run_scenarios(
        panel, masks, variables, models=models, universes=["All", "Big"],
        subperiods=2, winsor_levels=(1.0,), weights=("reference", "textbook"),
    )
    # 2 models × 2 universes × 3 windows × 2 weights, rows = Σ predictors
    assert len(frame) == 2 * 3 * 2 * (2 + 3)
    assert set(frame["window"]) == {"full", "sub1of2", "sub2of2"}
    assert set(frame["nw_weight"]) == {"reference", "textbook"}
    full = frame[(frame.window == "full") & (frame.model == "Model A")
                 & (frame.universe == "All")]
    sub = frame[(frame.window == "sub1of2") & (frame.model == "Model A")
                & (frame.universe == "All")]
    assert (sub["n_months"].to_numpy() < full["n_months"].to_numpy()).all()
    # dataclasses untouched by the sweep
    assert dataclasses.is_dataclass(models[0])


def test_subperiod_windows_partition():
    wins = subperiod_windows(601, 3)
    assert wins["full"] is None
    spans = [wins[k] for k in wins if k != "full"]
    assert spans[0][0] == 0 and spans[-1][1] == 601
    for (a, b), (c, d) in zip(spans, spans[1:]):
        assert b == c


def test_winsor_variant_tighter_only():
    rng = np.random.default_rng(29)
    x = rng.standard_normal((24, 200, 2))
    mask = rng.random((24, 200)) > 0.1
    x[~mask] = np.nan
    out = np.asarray(winsor_variant(x, mask, 5.0))
    # tighter clip: support shrinks, NaNs stay NaN, interior untouched
    assert np.isnan(out).sum() == np.isnan(x).sum()
    ok = ~np.isnan(x)
    assert (np.abs(out[ok]) <= np.abs(np.nanmax(np.abs(x))) + 1e-12).all()
    assert np.nanmax(out) <= np.nanmax(x)
    with pytest.raises(ValueError):
        winsor_variant(x, mask, 0.5)  # looser than the stored base clip


def test_pipeline_specgrid_hook(tmp_path):
    """``run_pipeline(make_specgrid=True)`` runs the scenario sweep on the
    Gram engine, returns the tidy frame, and saves the CSV artifact."""
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.pipeline import run_pipeline

    res = run_pipeline(
        synthetic=True, synthetic_config=SyntheticConfig(30, 48),
        make_figure=False, make_deciles=False, make_serving=False,
        make_specgrid=True, compile_pdf=False, output_dir=tmp_path,
    )
    frame = res.specgrid_scenarios
    assert frame is not None and len(frame) > 0
    assert {"model", "universe", "window", "coef", "tstat",
            "refereed"} <= set(frame.columns)
    assert "specgrid" in res.timer.durations
    assert (tmp_path / "specgrid_scenarios.csv").exists()


def test_winsorize_batched_bit_identical_to_per_column():
    """The satellite: the batched (V, T, N) winsorizer must reproduce the
    per-column loop bit-for-bit (including the min_obs pass-through and
    NaN propagation) — it is the same arithmetic, just one launch."""
    from fm_returnprediction_tpu.ops.quantiles import (
        winsorize_cs,
        winsorize_cs_batched,
    )

    rng = np.random.default_rng(31)
    t, n, v = 20, 150, 6
    vals = rng.standard_normal((v, t, n))
    vals[rng.random((v, t, n)) < 0.1] = np.nan
    mask = rng.random((t, n)) > 0.15
    # a min_obs month: fewer than 5 valid rows must pass through unclipped
    mask[3, 4:] = False
    vals_j = jnp.asarray(vals)
    mask_j = jnp.asarray(mask)
    batched = np.asarray(winsorize_cs_batched(vals_j, mask_j))
    for k in range(v):
        single = np.asarray(winsorize_cs(vals_j[k], mask_j))
        np.testing.assert_array_equal(batched[k], single, err_msg=f"col {k}")


def test_enrich_winsorized_matches_split_helpers():
    """The fused enrich+winsorize program (now on the batched winsorizer)
    still equals the split append→winsorize→scatter route — to FMA-level
    rounding: the two programs give XLA different fusion contexts for the
    interpolation mul-adds, so a handful of entries differ in the last
    ulp (≤5e-16 observed); anything larger is a real regression."""
    from fm_returnprediction_tpu.panel.characteristics import (
        _append_vars,
        _enrich_winsorized,
        _scatter_winsorized,
        _winsorize_columns,
    )

    rng = np.random.default_rng(37)
    t, n, k = 18, 40, 3
    values = rng.standard_normal((t, n, k))
    mask = rng.random((t, n)) > 0.2
    values[~mask] = np.nan
    extras = [rng.standard_normal((t, n)) for _ in range(2)]
    win_idx = (1, 3)

    fused = np.asarray(_enrich_winsorized(
        jnp.asarray(values), jnp.asarray(mask),
        [jnp.asarray(e) for e in extras], win_idx,
    ))
    appended = _append_vars(jnp.asarray(values), [jnp.asarray(e) for e in extras])
    win = _winsorize_columns(appended[:, :, list(win_idx)], jnp.asarray(mask))
    split = np.asarray(_scatter_winsorized(appended, win, list(win_idx)))
    both_nan = np.isnan(fused) & np.isnan(split)
    np.testing.assert_allclose(
        np.where(both_nan, 0.0, fused), np.where(both_nan, 0.0, split),
        rtol=0, atol=1e-14,
    )
