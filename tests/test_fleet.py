"""Serving-fleet semantics: replication, admission control, failover,
rollover, and the exactly-once journal proof.

The fleet's contract is layered on the single-service contracts the
serving tests already pin, so these tests assert the NEW semantics only:

- a fleet of 1 is differentially bit-identical to a bare ``ERService``
  under a deterministic submission pattern (same batches → same bits);
- a state swap under concurrent load and a mid-flight replica kill both
  leave the journal replay CLEAN — zero dropped, zero duplicated — with
  the kill path showing the requeues that made it survivable;
- admission control sheds with a typed, retriable 429
  (``ServiceOverloadError`` with retry-after evidence) and the
  shed → wait → retry → success path works;
- chaos-driven failover restores quoting with ZERO process-local
  compiles via the registry warm pool (``WarmReport`` evidence, PR 9);
- the supervisor state machine walks breach → drain → replace.
"""

import json
import threading

import numpy as np
import pytest

from fm_returnprediction_tpu.resilience.errors import (
    ServiceOverloadError,
    StateRolloverError,
)
from fm_returnprediction_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    fleet_kill_routed,
    fleet_stall_replica,
    fleet_trigger_staged_rollover,
    poison_serving_state_nan,
)
from fm_returnprediction_tpu.serving import (
    AdmissionPolicy,
    ERService,
    HashRing,
    MicroBatcher,
    QueueFullError,
    RequestJournal,
    ServingFleet,
    TokenBucket,
    build_serving_state,
    ingest_month,
    replay_journal,
)
from fm_returnprediction_tpu.serving.supervisor import (
    DEAD,
    DRAINING,
    HEALTHY,
    HealthPolicy,
)

pytestmark = pytest.mark.fleet

T, N, P = 48, 40, 3
WINDOW, MIN_PERIODS = 16, 8


def _make_panel(seed=2015):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((T, N, P)).astype(np.float32)
    beta = np.array([0.05, -0.02, 0.01], dtype=np.float32)
    y = (x @ beta + 0.02 * rng.standard_normal((T, N))).astype(np.float32)
    mask = rng.random((T, N)) > 0.1
    y = np.where(mask, y, np.nan).astype(np.float32)
    x = np.where(mask[..., None], x, np.nan).astype(np.float32)
    return y, x, mask


@pytest.fixture(scope="module")
def case():
    y, x, mask = _make_panel()
    state = build_serving_state(
        y, x, mask, window=WINDOW, min_periods=MIN_PERIODS
    )
    rng = np.random.default_rng(7)
    n_q = 120
    months = rng.integers(T // 2, T, n_q)
    firms = rng.integers(0, N, n_q)
    qx = x[months, firms]
    return y, x, mask, state, months, firms, qx


def _oracle(state, months, qx):
    """Reference answers from a bare, manually-pumped service."""
    with ERService(state, max_batch=8, auto_flush=False) as ref:
        futs = [ref.submit(int(m), q) for m, q in zip(months, qx)]
        ref.batcher.drain()
        return np.asarray([f.result(timeout=5) for f in futs])


# -- fleet-of-1 differential -------------------------------------------------


def test_fleet_of_one_bit_identical_to_bare_service(case):
    """Same deterministic submission pattern → same batches → the fleet
    adds routing/journal bookkeeping but must not move one bit of the
    answer."""
    _, _, _, state, months, firms, qx = case
    want = _oracle(state, months, qx)
    with ServingFleet(state, 1, max_batch=8, auto_flush=False) as fleet:
        futs = [fleet.submit(int(m), q) for m, q in zip(months, qx)]
        fleet.flush_all()
        got = np.asarray([f.result(timeout=5) for f in futs])
    assert np.array_equal(got, want, equal_nan=True)


# -- exactly-once across a state swap under load -----------------------------


def test_swap_under_load_journal_proves_exactly_once(case, tmp_path):
    """Concurrent query threads; the ``fleet.swap_mid_flight`` chaos site
    fires a STAGED two-phase rollover between two specific admits. Every
    request resolves, the journal replay is clean, and the answers match
    the oracle (old months are identical across versions)."""
    y, x, mask, state, months, firms, qx = case
    want = _oracle(state, months, qx)
    new_state = ingest_month(
        state, y[-1], x[-1], mask[-1], np.datetime64("2031-01-31", "ns")
    )
    journal = tmp_path / "swap.jsonl"
    results = np.empty(len(months))
    with ServingFleet(state, 2, max_batch=8, max_latency_ms=1.0,
                      journal=journal) as fleet:
        fleet.stage_rollover(new_state)
        with FaultPlan({
            "fleet.swap_mid_flight": FaultSpec(
                skip=len(months) // 2, times=1,
                mutate=fleet_trigger_staged_rollover,
            ),
        }) as plan:
            def worker(lo, hi):
                for k in range(lo, hi):
                    results[k] = fleet.query(int(months[k]), qx[k])

            threads = [
                threading.Thread(target=worker, args=(k * 30, (k + 1) * 30))
                for k in range(4)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        assert plan.fired["fleet.swap_mid_flight"] == 1
        assert fleet.drain(timeout=10)
        assert fleet.version == 1
        for rep in fleet.stats()["replicas"].values():
            assert rep["state"] == HEALTHY
    # bucket composition differs under threading → ULP-level f32 wiggle,
    # same tolerance the serving stream differential uses
    np.testing.assert_allclose(
        results, want, rtol=1e-6, atol=1e-9, equal_nan=True
    )
    replay = replay_journal(journal)
    assert replay.clean, (replay.dropped, replay.duplicated, replay.invalid)
    assert replay.n_admitted == len(months) == replay.n_done
    marks = [m["label"] for m in replay.marks]
    assert "rollover_begin" in marks and "rollover_commit" in marks


# -- exactly-once across a mid-flight replica kill ---------------------------


def test_kill_under_load_journal_proves_exactly_once(case, tmp_path):
    """Deterministic mid-flight kill: requests queue unflushed, the
    ``fleet.replica_kill`` site kills the replica the 21st routed request
    is IN FLIGHT on — every request it stranded requeues onto the
    survivor and completes. Zero dropped, zero duplicated, requeues > 0."""
    _, _, _, state, months, firms, qx = case
    want = _oracle(state, months, qx)
    journal = tmp_path / "kill.jsonl"
    with ServingFleet(state, 2, max_batch=8, auto_flush=False,
                      journal=journal) as fleet:
        with FaultPlan({
            "fleet.replica_kill": FaultSpec(
                skip=20, times=1, mutate=fleet_kill_routed(),
            ),
        }) as plan:
            futs = [fleet.submit(int(m), q) for m, q in zip(months, qx)]
        assert plan.fired["fleet.replica_kill"] == 1
        # pump until every future resolves (requeued work lands in the
        # survivor's queue after its first drain)
        for _ in range(4):
            fleet.flush_all()
        got = np.asarray([f.result(timeout=5) for f in futs])
        stats = fleet.stats()
        assert stats["requeues_total"] > 0
        assert len(stats["dead_replicas"]) == 1
        # the dead replica's lifetime counters FOLD into the aggregate —
        # agg_n_done is monotone across kills (a scraper's rate() over
        # the exported gauge must never go negative)
        assert stats["agg_n_done"] == len(months)
        # supervision replaces the corpse and quoting is fully restored
        actions = fleet.supervisor.tick()
        assert any(a.startswith("failover:") for a in actions)
        assert fleet.stats()["healthy_replicas"] == 2
        post_fut = fleet.submit(int(months[0]), qx[0])
        fleet.flush_all()
        post = post_fut.result(timeout=5)
    np.testing.assert_allclose(
        got, want, rtol=1e-6, atol=1e-9, equal_nan=True
    )
    np.testing.assert_allclose(post, want[0], rtol=1e-6, atol=1e-9)
    replay = replay_journal(journal)
    assert replay.clean, (replay.dropped, replay.duplicated, replay.invalid)
    assert replay.n_requeues > 0
    assert replay.n_admitted == replay.n_done  # nothing lost, nothing twice


# -- admission control -------------------------------------------------------


def test_admission_shed_retry_success(case, tmp_path):
    """Token-bucket shed → typed retriable 429 with a retry-after hint →
    advancing the (injected) clock by exactly that hint admits the
    retry. The journal shows the shed as a terminal, not a drop."""
    _, _, _, state, months, _, qx = case
    clk = [0.0]
    journal = tmp_path / "shed.jsonl"
    with ServingFleet(
        state, 1, max_batch=8, auto_flush=False, journal=journal,
        admission=AdmissionPolicy(rate_per_s=10.0, burst=2.0),
        admission_clock=lambda: clk[0],
    ) as fleet:
        f1 = fleet.submit(int(months[0]), qx[0])
        f2 = fleet.submit(int(months[1]), qx[1])
        with pytest.raises(ServiceOverloadError) as err:
            fleet.submit(int(months[2]), qx[2])
        assert err.value.reason == "token_bucket"
        assert err.value.retry_after_s > 0
        # the hint is honest: advancing the clock by it admits the retry
        clk[0] += err.value.retry_after_s
        f3 = fleet.submit(int(months[2]), qx[2])
        fleet.flush_all()
        for f in (f1, f2, f3):
            f.result(timeout=5)
        assert fleet.stats()["shed_total"] == 1
    replay = replay_journal(journal)
    assert replay.clean
    assert replay.n_shed == 1 and replay.n_done == 3


def test_admission_occupancy_shed_carries_queue_evidence(case):
    """Queue-occupancy shedding fires BEFORE any replica queue is hit and
    its error carries the depth/ceiling evidence (the same fields
    ``QueueFullError`` now exposes, one layer earlier)."""
    _, _, _, state, months, _, qx = case
    with ServingFleet(
        state, 2, max_batch=8, max_queue=4, auto_flush=False,
        admission=AdmissionPolicy(max_occupancy=0.75),
    ) as fleet:
        futs = [fleet.submit(int(months[k]), qx[k]) for k in range(6)]
        with pytest.raises(ServiceOverloadError) as err:
            fleet.submit(int(months[6]), qx[6])
        assert err.value.reason == "queue_occupancy"
        assert err.value.queue_depth == 6
        assert err.value.queue_ceiling == 8
        assert err.value.occupancy == pytest.approx(0.75)
        assert err.value.retry_after_s > 0
        fleet.flush_all()
        retry = fleet.submit(int(months[6]), qx[6])
        fleet.flush_all()
        for f in [*futs, retry]:
            assert isinstance(f.result(timeout=5), float)


def test_queue_full_error_carries_occupancy_and_ceiling():
    """Satellite: ``MicroBatcher.submit`` backpressure now discloses the
    queue evidence in the exception itself."""
    mb = MicroBatcher(lambda m, x, v: np.zeros(len(m)), max_queue=2,
                      auto_flush=False)
    mb.submit(0, np.zeros(3))
    mb.submit(0, np.zeros(3))
    with pytest.raises(QueueFullError) as err:
        mb.submit(0, np.zeros(3))
    assert err.value.queue_depth == 2
    assert err.value.max_queue == 2
    assert err.value.occupancy == 1.0
    assert "2 pending of 2" in str(err.value)
    mb.close()


def test_token_bucket_deterministic_refill():
    clk = [0.0]
    tb = TokenBucket(rate_per_s=4.0, burst=2.0, clock=lambda: clk[0])
    assert tb.try_acquire() is None
    assert tb.try_acquire() is None
    wait = tb.try_acquire()
    assert wait == pytest.approx(0.25)
    clk[0] += 0.25
    assert tb.try_acquire() is None
    clk[0] += 10.0  # refill caps at burst
    assert tb.try_acquire() is None
    assert tb.try_acquire() is None
    assert tb.try_acquire() is not None


# -- routing -----------------------------------------------------------------


def test_hash_ring_consistency_and_exclusion():
    ring = HashRing(vnodes=32)
    for rid in ("r0", "r1", "r2"):
        ring.add(rid)
    keys = [f"k{i}" for i in range(200)]
    before = {k: ring.route(k) for k in keys}
    # deterministic: a rebuilt ring with the same members agrees exactly
    ring2 = HashRing(vnodes=32)
    for rid in ("r2", "r0", "r1"):  # insertion order must not matter
        ring2.add(rid)
    assert {k: ring2.route(k) for k in keys} == before
    # removing one member only remaps ITS keys (consistent hashing)
    ring.remove("r2")
    for k in keys:
        if before[k] != "r2":
            assert ring.route(k) == before[k]
        else:
            assert ring.route(k) in ("r0", "r1")
    # exclusion == removal for routing purposes, without membership churn
    assert all(
        ring2.route(k, exclude={"r2"}) == ring.route(k) for k in keys
    )
    assert ring.route("k0", exclude={"r0", "r1"}) is None


# -- rollover protocol -------------------------------------------------------


def test_rollover_poison_state_aborts_with_zero_flips(case, tmp_path):
    """The ``fleet.poison_state`` site corrupts the SECOND replica's
    rollover candidate: the two-phase protocol must abort with zero
    commits — including the first replica, whose prepare already
    succeeded — so the fleet can never split across versions."""
    y, x, mask, state, months, firms, qx = case
    want = _oracle(state, months, qx)
    new_state = ingest_month(
        state, y[-1], x[-1], mask[-1], np.datetime64("2031-01-31", "ns")
    )
    journal = tmp_path / "poison.jsonl"
    with ServingFleet(state, 2, max_batch=8, auto_flush=False,
                      journal=journal) as fleet:
        with FaultPlan({
            "fleet.poison_state": FaultSpec(
                skip=1, times=1, mutate=poison_serving_state_nan,
            ),
        }) as plan:
            with pytest.raises(StateRolloverError) as err:
                fleet.rollover(new_state)
        assert plan.fired["fleet.poison_state"] == 1
        assert "no replica flipped" in str(err.value)
        assert fleet.version == 0
        # every replica still serves the OLD version, bit-identically
        for rep in fleet.stats()["replicas"].values():
            assert rep["state"] == HEALTHY
        futs = [fleet.submit(int(m), q) for m, q in zip(months, qx)]
        fleet.flush_all()
        got = np.asarray([f.result(timeout=5) for f in futs])
        assert np.array_equal(got, want, equal_nan=True)
        # a later clean rollover still lands
        assert fleet.rollover(new_state) == 1
    marks = [m["label"] for m in replay_journal(journal).marks]
    assert "rollover_abort" in marks
    assert marks.count("rollover_commit") == 1


def test_rollover_rejects_non_append_candidate(case):
    _, _, _, state, *_ = case
    import dataclasses

    with ServingFleet(state, 1, max_batch=8, auto_flush=False) as fleet:
        shrunk = dataclasses.replace(
            state,
            months=state.months[:-1], coef=state.coef[:-1],
            month_valid=state.month_valid[:-1],
            slopes_bar=state.slopes_bar[:-1],
            intercept_bar=state.intercept_bar[:-1],
            x_lo=state.x_lo[:-1], x_hi=state.x_hi[:-1],
            gram=state.gram[:-1], moment=state.moment[:-1],
            n_obs=state.n_obs[:-1], ysum=state.ysum[:-1], yy=state.yy[:-1],
        )
        with pytest.raises(StateRolloverError, match="backwards"):
            fleet.rollover(shrunk)
        assert fleet.version == 0


# -- supervision -------------------------------------------------------------


def test_supervisor_drains_and_replaces_poisoned_replica(case):
    """Quarantine breach walks the machine: HEALTHY → (probe breach) →
    DRAINING (router excludes it) → idle → replaced, failover counted."""
    _, _, _, state, months, _, qx = case
    with ServingFleet(
        state, 2, max_batch=8, auto_flush=False,
        health=HealthPolicy(max_quarantined_months=0,
                            consecutive_breaches=1),
    ) as fleet:
        victim = sorted(fleet.replica_states())[0]
        rep = fleet.replica(victim)
        bad = np.full((N, P), np.nan, dtype=np.float32)
        assert not rep.service.ingest_month(
            np.full(N, np.nan), bad, np.ones(N, bool),
            np.datetime64("2070-01-31", "ns"),
        )
        actions = fleet.supervisor.tick()
        assert any(a.startswith(f"drain:{victim}") for a in actions)
        assert fleet.replica_states()[victim] == DRAINING
        # draining replicas take no new traffic
        futs = [fleet.submit(int(months[k]), qx[k]) for k in range(10)]
        assert fleet.replica(victim).service.batcher.queue_depth == 0
        fleet.flush_all()
        for f in futs:
            f.result(timeout=5)
        actions = fleet.supervisor.tick()
        assert any(a.startswith(f"replace:{victim}") for a in actions)
        assert victim not in fleet.replica_states()
        stats = fleet.stats()
        assert stats["healthy_replicas"] == 2
        assert stats["failovers_total"] == 1
        assert victim in stats["replaced"]


def test_supervisor_stall_breach_via_dispatch_timeout(case):
    """A stalled replica (``fleet.replica_stall``) trips the PR-2
    dispatch watchdog; its requests requeue to the survivor and the
    supervisor's timeout-rate probe drains the staller."""
    _, _, _, state, months, _, qx = case
    with ServingFleet(
        state, 2, max_batch=8, auto_flush=False, dispatch_timeout_s=0.15,
        health=HealthPolicy(max_dispatch_timeout_rate=0.0,
                            consecutive_breaches=1),
    ) as fleet:
        victim = sorted(fleet.replica_states())[0]
        with FaultPlan({
            "fleet.replica_stall": FaultSpec(
                times=-1, mutate=fleet_stall_replica(victim, 0.5),
            ),
        }):
            futs = [fleet.submit(int(months[k]), qx[k]) for k in range(12)]
            for _ in range(3):
                fleet.flush_all()
        got = [f.result(timeout=5) for f in futs]
        assert len(got) == 12
        assert fleet.stats()["requeues_total"] > 0
        actions = fleet.supervisor.tick()
        assert any(a.startswith(f"drain:{victim}") for a in actions)


def test_supervisor_heartbeat_kill_on_dead_flusher(case):
    """A replica whose flusher thread died fails the heartbeat probe and
    is killed + failed over (no polite drain for a corpse)."""
    _, _, _, state, *_ = case
    with ServingFleet(state, 2, max_batch=8) as fleet:  # auto_flush on
        victim = sorted(fleet.replica_states())[0]
        rep = fleet.replica(victim)
        # simulate a crashed flusher: close the thread without the fleet
        rep.service.batcher.close()
        actions = fleet.supervisor.tick()
        assert any(a.startswith(f"kill:{victim}") for a in actions)
        assert fleet.replica_states()[victim] == DEAD
        actions = fleet.supervisor.tick()
        assert any(a.startswith(f"failover:{victim}") for a in actions)
        assert fleet.stats()["healthy_replicas"] == 2


# -- warm-pool failover (the acceptance criterion) ---------------------------


def test_chaos_failover_restores_quoting_with_zero_compiles(case, tmp_path):
    """With a populated registry, EVERY replica start — including the
    chaos-driven failover replacement — is compile-free: the WarmReport
    shows all bucket programs deserialized, zero fresh compiles, zero
    serving-bucket traces (PR-9 evidence)."""
    from fm_returnprediction_tpu.registry.store import using_registry

    _, _, _, state, months, _, qx = case
    reg_dir = tmp_path / "registry"
    # one populating warm-up stores every bucket executable
    with using_registry(reg_dir):
        ERService(state, max_batch=8, auto_flush=False).close()
    with ServingFleet(state, 2, max_batch=8, auto_flush=False,
                      registry_dir=reg_dir) as fleet:
        for rid, report in fleet.warm_reports.items():
            assert report.zero_compile, (rid, report)
        victim = sorted(fleet.replica_states())[0]
        with FaultPlan({
            "fleet.replica_kill": FaultSpec(
                times=1, mutate=fleet_kill_routed(victim),
            ),
        }):
            futs = [fleet.submit(int(months[k]), qx[k]) for k in range(20)]
        for _ in range(3):
            fleet.flush_all()
        for f in futs:
            f.result(timeout=5)
        actions = fleet.supervisor.tick()
        assert any(a.startswith("failover:") for a in actions)
        (replacement,) = [
            rid for rid in fleet.replica_states() if rid != victim
            and rid not in ("r0", "r1")
        ]
        report = fleet.warm_reports[replacement]
        assert report.zero_compile, report
        assert report.fresh_compiles == 0
        assert report.deserialized == len(
            fleet.replica(replacement).service.executor.buckets()
        )
        # quoting restored through the replacement
        want = _oracle(state, months[:20], qx[:20])
        futs = [fleet.submit(int(months[k]), qx[k]) for k in range(20)]
        fleet.flush_all()
        got = np.asarray([f.result(timeout=5) for f in futs])
        assert np.array_equal(got, want, equal_nan=True)


# -- journal FSM -------------------------------------------------------------


def test_journal_replay_flags_drops_duplicates_and_violations(tmp_path):
    path = tmp_path / "bad.jsonl"
    lines = [
        {"seq": 1, "ev": "admit", "req": 1},
        {"seq": 2, "ev": "route", "req": 1, "replica": "r0"},
        # req 1 never terminates → dropped
        {"seq": 3, "ev": "admit", "req": 2},
        {"seq": 4, "ev": "route", "req": 2, "replica": "r0"},
        {"seq": 5, "ev": "done", "req": 2},
        {"seq": 6, "ev": "done", "req": 2},       # duplicated terminal
        {"seq": 7, "ev": "route", "req": 3},      # route without admit
        {"seq": 8, "ev": "shed", "req": 4},       # clean front-door shed
    ]
    with open(path, "w") as fh:
        for rec in lines:
            fh.write(json.dumps(rec) + "\n")
        fh.write('{"seq": 9, "ev": "admit", "req":')  # torn tail
    replay = replay_journal(path)
    assert replay.dropped == (1, 3)
    assert replay.duplicated == (2,)
    assert not replay.clean
    assert any("route from state" in v for v in replay.invalid)
    assert any("torn" in v for v in replay.invalid)
    assert replay.n_shed == 1


def test_raising_chaos_site_cannot_strand_accounting(case, tmp_path):
    """A RAISING spec at ``fleet.swap_mid_flight`` (not the documented
    mutate) escapes submit — but the admitted request must still reach a
    terminal journal event and release ``drain()``; nothing strands."""
    from fm_returnprediction_tpu.resilience.errors import InjectedFault

    _, _, _, state, months, _, qx = case
    journal = tmp_path / "raise.jsonl"
    with ServingFleet(state, 1, max_batch=8, auto_flush=False,
                      journal=journal) as fleet:
        with FaultPlan({"fleet.swap_mid_flight": FaultSpec(times=1)}):
            with pytest.raises(InjectedFault):
                fleet.submit(int(months[0]), qx[0])
        assert fleet.drain(timeout=1), "outstanding leaked"
        ok = fleet.submit(int(months[1]), qx[1])
        fleet.flush_all()
        assert isinstance(ok.result(timeout=5), float)
    replay = replay_journal(journal)
    assert replay.clean, (replay.dropped, replay.invalid)
    assert replay.n_error == 1 and replay.n_done == 1


def test_journal_rotates_reused_path(tmp_path):
    """Request ids restart with every fleet, so a reused journal path
    (FMRP_FLEET_JOURNAL) must ROTATE the previous session's file instead
    of appending — otherwise a healthy second run replays as a wall of
    false duplicates. Each file replays standalone and clean."""
    path = tmp_path / "j.jsonl"
    with RequestJournal(path) as j1:
        assert j1.rotated_to is None
        j1.append("admit", 1)
        j1.append("route", 1, replica="r0")
        j1.append("done", 1)
    with RequestJournal(path) as j2:
        rotated = j2.rotated_to
        assert rotated is not None and rotated.exists()
        j2.append("admit", 1)          # same req id as session 1
        j2.append("shed", 1)
    for p in (path, rotated):
        replay = replay_journal(p)
        assert replay.clean, (p, replay.duplicated, replay.invalid)
    assert replay_journal(path).n_shed == 1
    assert replay_journal(rotated).n_done == 1


def test_journal_clean_sequences(tmp_path):
    path = tmp_path / "good.jsonl"
    lines = [
        {"seq": 1, "ev": "admit", "req": 1},
        {"seq": 2, "ev": "route", "req": 1, "replica": "r0"},
        {"seq": 3, "ev": "requeue", "req": 1, "replica": "r0"},
        {"seq": 4, "ev": "route", "req": 1, "replica": "r1"},
        {"seq": 5, "ev": "mark", "label": "rollover_begin"},
        {"seq": 6, "ev": "done", "req": 1},
        {"seq": 7, "ev": "shed", "req": 2},
    ]
    with open(path, "w") as fh:
        for rec in lines:
            fh.write(json.dumps(rec) + "\n")
    replay = replay_journal(path)
    assert replay.clean
    assert replay.n_requeues == 1
    assert [m["label"] for m in replay.marks] == ["rollover_begin"]


# -- instrumentation / knobs -------------------------------------------------


def test_prometheus_per_replica_labels_and_fleet_gauges(case):
    _, _, _, state, months, _, qx = case
    with ServingFleet(state, 2, max_batch=8, auto_flush=False) as fleet:
        f = fleet.submit(int(months[0]), qx[0])
        fleet.flush_all()
        f.result(timeout=5)
        text = fleet.prometheus_metrics()
    for family in (
        "fmrp_serving_requests_done_total",
        "fmrp_serving_executable_cache_hits_total",
    ):
        assert f'{family}{{replica="r0"}}' in text
        assert f'{family}{{replica="r1"}}' in text
    for gauge in (
        "fmrp_fleet_healthy_replicas 2",
        "fmrp_fleet_size 2",
        "fmrp_fleet_service_version 0",
    ):
        assert gauge in text
    # exposition-format discipline (the PR-6 hardening): HELP before
    # series, and every series line parses as name{labels} value
    assert "# HELP fmrp_fleet_healthy_replicas" in text


def test_fleet_env_knobs(case, monkeypatch):
    _, _, _, state, *_ = case
    monkeypatch.setenv("FMRP_FLEET_SIZE", "3")
    monkeypatch.setenv("FMRP_FLEET_RATE", "50")
    monkeypatch.setenv("FMRP_FLEET_BURST", "7")
    monkeypatch.setenv("FMRP_FLEET_SHED_OCCUPANCY", "0.5")
    policy = AdmissionPolicy.from_env()
    assert policy.rate_per_s == 50.0
    assert policy.burst == 7.0
    assert policy.max_occupancy == 0.5
    with ServingFleet(state, max_batch=8, auto_flush=False) as fleet:
        assert fleet.stats()["fleet_size"] == 3
        assert fleet._bucket is not None


def test_single_service_swap_state_publishes_behind_warm_executor(case):
    """The generalized PR-1 discipline on a bare service: ``swap_state``
    flips to an externally built version with the executor already warm
    (no misses after the swap) and old-month answers unchanged."""
    y, x, mask, state, months, firms, qx = case
    want = _oracle(state, months, qx)
    new_state = ingest_month(
        state, y[-1], x[-1], mask[-1], np.datetime64("2031-01-31", "ns")
    )
    with ERService(state, max_batch=8, auto_flush=False) as svc:
        svc.swap_state(new_state)
        assert svc.state is new_state
        futs = [svc.submit(int(m), q) for m, q in zip(months, qx)]
        svc.batcher.drain()
        got = np.asarray([f.result(timeout=5) for f in futs])
        assert svc.stats()["executable_cache_misses"] == 0
    assert np.array_equal(got, want, equal_nan=True)
