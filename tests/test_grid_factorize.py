"""Month-axis Gram factorization vs the legacy per-window contraction.

The ISSUE-14 part-(a) contracts:

- ``unique_pairs`` collapses the spec axis to distinct (universe,
  col_sel) pairs with a faithful inverse map, and its ``pad_to``
  signature-pad repeats are inert;
- stats-level exactness: ``contract_spec_grams(window=None)`` +
  ``expand_window_stats`` reproduces the windowed contraction — counts
  EXACTLY, moments at f64 ≤ 1e-13·scale (both XLA and pallas routes) —
  across thin months, all-NaN columns, mask edges and coreset row
  weights;
- end-to-end differential: ``run_spec_grid(factorize="on")`` ==
  ``factorize="off"`` (the byte-pinned legacy oracle) at f64 ≤ 1e-13 and
  f32 1e-6 relative, NaN patterns identical;
- the contraction-work ledger tracks PAIRS, not S, under the factorized
  route, and "auto" resolves on exactly for window sweeps;
- the knob's guardrails: env resolution, invalid values, and the
  single-device-only rule (mesh / procs reject ``"on"``).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from fm_returnprediction_tpu.specgrid.grams import (
    contract_spec_grams,
    resolve_gram_factorize,
    shared_center,
    unique_pairs,
)
from fm_returnprediction_tpu.specgrid.solve import (
    contraction_counts,
    expand_window_stats,
    run_spec_grid,
)
from fm_returnprediction_tpu.specgrid.specs import Spec, SpecGrid

pytestmark = pytest.mark.specgrid


def _panel(seed=0, t=18, n=160, p=5, dtype=np.float64):
    """A panel exercising every parity edge at once: NaN sprinkle, an
    all-NaN firm column, a y-less firm, a thin month (nearly-empty
    universe), and window masks hitting both calendar edges."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(dtype)
    x[rng.random(x.shape) < 0.08] = np.nan
    x[:, 7, 2] = np.nan                       # an all-NaN firm column
    y = rng.standard_normal((t, n)).astype(dtype)
    y[rng.random(y.shape) < 0.12] = np.nan
    y[:, 11] = np.nan                         # a y-less firm
    uni_all = np.ones((t, n), bool)
    uni_thin = rng.random((t, n)) > 0.4
    uni_thin[3, 6:] = False                   # thin month: 6 firms survive
    return y, x, {"All": uni_all, "Thin": uni_thin}


def _window_sweep_grid(t, p, names=None):
    """4 windows × 2 universes × 2 sets = 16 specs over 4 unique pairs;
    windows hit both calendar edges (the mask-edge case)."""
    names = names or tuple(f"c{i}" for i in range(p))
    windows = (None, (0, t // 3), (t - t // 3, t), (t // 4, 3 * t // 4))
    specs = tuple(
        Spec(f"{set_name}_{uni}_{w}", cols, uni, window=w)
        for set_name, cols in (("m2", names[:2]), ("m5", names))
        for uni in ("All", "Thin")
        for w in windows
    )
    return SpecGrid(specs, union=names)


def _grid_tensors(grid, masks, t):
    names = list(masks)
    universes = jnp.asarray(np.stack([masks[u] for u in names]))
    uidx = grid.universe_index(names)
    col_sel = grid.column_selector()
    window = grid.window_masks(t)
    return universes, uidx, col_sel, window


# -- unique_pairs ------------------------------------------------------------

def test_unique_pairs_dedup_and_inverse():
    rng = np.random.default_rng(1)
    base_sel = rng.random((3, 6)) > 0.5
    uidx = np.array([0, 0, 1, 0, 1, 0, 0, 1], np.int64)
    col_sel = base_sel[[0, 1, 0, 0, 0, 1, 2, 0]]
    u_u, c_u, pidx = unique_pairs(uidx, col_sel)
    # the inverse map reconstructs every spec's pair exactly
    np.testing.assert_array_equal(u_u[pidx], uidx)
    np.testing.assert_array_equal(c_u[pidx], col_sel)
    # distinctness: no two kept rows agree on (universe, columns)
    keys = {(int(u), c.tobytes()) for u, c in zip(u_u, c_u)}
    assert len(keys) == u_u.shape[0] < uidx.shape[0]


def test_unique_pairs_padding_is_inert():
    uidx = np.array([0, 1, 0], np.int64)
    col_sel = np.array([[1, 0], [1, 0], [1, 0]], bool)
    u_u, c_u, pidx = unique_pairs(uidx, col_sel, pad_to=5)
    assert u_u.shape == (5,) and c_u.shape == (5, 2)
    # pads repeat pair 0 and pair_idx never points at them
    np.testing.assert_array_equal(u_u[2:], [u_u[0]] * 3)
    assert pidx.max() <= 1
    with pytest.raises(ValueError, match="below"):
        unique_pairs(uidx, col_sel, pad_to=1)


# -- stats-level exactness ---------------------------------------------------

@pytest.mark.parametrize("route", ["xla", "pallas"])
def test_window_none_plus_expand_matches_windowed_contraction(route):
    y, x, masks = _panel()
    t = y.shape[0]
    grid = _window_sweep_grid(t, x.shape[2])
    universes, uidx, col_sel, window = _grid_tensors(grid, masks, t)
    kw = ({"route": "pallas", "block_n": 64, "interpret": True}
          if route == "pallas" else {})
    ref = contract_spec_grams(jnp.asarray(y), jnp.asarray(x), universes,
                              jnp.asarray(uidx), jnp.asarray(col_sel),
                              jnp.asarray(window), **kw)
    u_u, c_u, pidx = unique_pairs(uidx, col_sel)
    assert u_u.shape[0] == 4  # 2 sets × 2 universes, windows collapsed
    pair = contract_spec_grams(jnp.asarray(y), jnp.asarray(x), universes,
                               jnp.asarray(u_u), jnp.asarray(c_u), None,
                               **kw)
    got = expand_window_stats(pair, jnp.asarray(pidx), jnp.asarray(window))
    for name in ("gram", "moment", "n", "ysum", "yy", "center"):
        a = np.asarray(getattr(ref, name))
        b = np.asarray(getattr(got, name))
        if name == "n":
            np.testing.assert_array_equal(a, b, err_msg=name)
        else:
            scale = max(np.max(np.abs(a)), 1.0)
            np.testing.assert_allclose(b, a, rtol=0, atol=1e-13 * scale,
                                       err_msg=name)


def test_expand_exact_under_coreset_row_weights():
    y, x, masks = _panel(seed=3)
    t, n = y.shape
    rng = np.random.default_rng(9)
    rw = jnp.asarray(rng.random((t, n)) * 2.0)  # importance weights
    grid = _window_sweep_grid(t, x.shape[2])
    universes, uidx, col_sel, window = _grid_tensors(grid, masks, t)
    ref = contract_spec_grams(jnp.asarray(y), jnp.asarray(x), universes,
                              jnp.asarray(uidx), jnp.asarray(col_sel),
                              jnp.asarray(window), row_weights=rw)
    u_u, c_u, pidx = unique_pairs(uidx, col_sel)
    pair = contract_spec_grams(jnp.asarray(y), jnp.asarray(x), universes,
                               jnp.asarray(u_u), jnp.asarray(c_u), None,
                               row_weights=rw)
    got = expand_window_stats(pair, jnp.asarray(pidx), jnp.asarray(window))
    for name in ("gram", "moment", "n", "ysum", "yy"):
        a = np.asarray(getattr(ref, name))
        scale = max(np.max(np.abs(a)), 1.0)
        np.testing.assert_allclose(np.asarray(getattr(got, name)), a,
                                   rtol=0, atol=1e-13 * scale, err_msg=name)


# -- end-to-end differential -------------------------------------------------

def _assert_grid_parity(off, on, atol, tstat_atol):
    for f in ("slopes", "r2", "coef", "nw_se", "mean_r2", "mean_n"):
        a = np.asarray(getattr(off, f), float)
        b = np.asarray(getattr(on, f), float)
        np.testing.assert_array_equal(np.isnan(a), np.isnan(b), err_msg=f)
        scale = max(np.nanmax(np.abs(a), initial=0.0), 1.0)
        np.testing.assert_allclose(b, a, rtol=0, atol=atol * scale,
                                   equal_nan=True, err_msg=f)
    a, b = np.asarray(off.tstat, float), np.asarray(on.tstat, float)
    np.testing.assert_array_equal(np.isnan(a), np.isnan(b), err_msg="tstat")
    scale = max(np.nanmax(np.abs(a), initial=0.0), 1.0)
    np.testing.assert_allclose(b, a, rtol=0, atol=tstat_atol * scale,
                               equal_nan=True, err_msg="tstat")
    # month counts are EXACTLY equal — zeroed out-of-window months are
    # the same zeros the legacy contraction produced
    np.testing.assert_array_equal(off.n_months, on.n_months)
    np.testing.assert_array_equal(off.month_valid, on.month_valid)
    np.testing.assert_array_equal(off.n_obs, on.n_obs)


def test_factorized_grid_parity_f64():
    y, x, masks = _panel()
    grid = _window_sweep_grid(y.shape[0], x.shape[2])
    off = run_spec_grid(y, x, masks, grid, factorize="off")
    on = run_spec_grid(y, x, masks, grid, factorize="on")
    _assert_grid_parity(off, on, atol=1e-13, tstat_atol=1e-11)


def test_factorized_grid_parity_f32():
    y, x, masks = _panel(seed=7, dtype=np.float32)
    grid = _window_sweep_grid(y.shape[0], x.shape[2])
    off = run_spec_grid(y, x, masks, grid, factorize="off")
    on = run_spec_grid(y, x, masks, grid, factorize="on")
    # f32: 1e-6 RELATIVE (absolute diffs scale with the Gram entries);
    # the t-stat divides two near-equal roundings, so it gets headroom
    _assert_grid_parity(off, on, atol=1e-6, tstat_atol=1e-4)


def test_factorized_grid_parity_coreset_weights():
    y, x, masks = _panel(seed=5)
    t, n = y.shape
    rw = np.random.default_rng(4).random((t, n)) * 3.0
    grid = _window_sweep_grid(t, x.shape[2])
    off = run_spec_grid(y, x, masks, grid, row_weights=rw, referee=False,
                        factorize="off")
    on = run_spec_grid(y, x, masks, grid, row_weights=rw, referee=False,
                       factorize="on")
    _assert_grid_parity(off, on, atol=1e-13, tstat_atol=1e-11)


# -- contraction-work ledger -------------------------------------------------

def test_contraction_counts_track_pairs_not_specs():
    y, x, masks = _panel(seed=11)
    grid = _window_sweep_grid(y.shape[0], x.shape[2])
    s = len(grid)
    before = contraction_counts()
    run_spec_grid(y, x, masks, grid, factorize="on")
    after = contraction_counts()
    d = {k: after.get(k, 0) - before.get(k, 0) for k in after}
    assert d.get("specs_solved") == s
    assert d.get("pairs_unique") == 4
    assert d.get("pairs_contracted") == 4 < s
    assert d.get("specs_contracted", 0) == 0
    before = contraction_counts()
    run_spec_grid(y, x, masks, grid, factorize="off")
    after = contraction_counts()
    assert after.get("specs_contracted", 0) - before.get(
        "specs_contracted", 0) == s


def test_auto_factorizes_window_sweeps_only():
    # the per-CALL contraction ledger (not the per-trace counter — jit
    # caching makes traces shape-dependent across the test session)
    y, x, masks = _panel(seed=13)
    t, p = y.shape[0], x.shape[2]
    names = tuple(f"c{i}" for i in range(p))
    sweep = _window_sweep_grid(t, p)
    before = contraction_counts()
    run_spec_grid(y, x, masks, sweep)     # factorize defaults to "auto"
    after = contraction_counts()
    assert after.get("pairs_contracted", 0) > before.get(
        "pairs_contracted", 0)
    # every pair distinct → auto keeps the legacy byte-pinned program
    flat = SpecGrid(
        (Spec("a", names[:2], "All"), Spec("b", names[:3], "Thin")),
        union=names,
    )
    before = contraction_counts()
    run_spec_grid(y, x, masks, flat)
    after = contraction_counts()
    assert after.get("pairs_contracted", 0) == before.get(
        "pairs_contracted", 0)
    assert after.get("specs_contracted", 0) - before.get(
        "specs_contracted", 0) == len(flat)


# -- knob guardrails ---------------------------------------------------------

def test_factorize_resolution(monkeypatch):
    monkeypatch.delenv("FMRP_GRAM_FACTORIZE", raising=False)
    assert resolve_gram_factorize() == "auto"
    monkeypatch.setenv("FMRP_GRAM_FACTORIZE", "on")
    assert resolve_gram_factorize() == "on"
    assert resolve_gram_factorize("off") == "off"  # arg beats env
    monkeypatch.setenv("FMRP_GRAM_FACTORIZE", "sometimes")
    with pytest.raises(ValueError, match="factorize"):
        resolve_gram_factorize()


def test_factorize_on_rejected_on_mesh_and_procs():
    names = ("c0",)
    grid = SpecGrid((Spec("m", names, "all"),), union=names)
    y = np.zeros((3, 8))
    x = np.zeros((3, 8, 1))
    masks = {"all": np.ones((3, 8), bool)}
    with pytest.raises(ValueError, match="single-device"):
        run_spec_grid(y, x, masks, grid, mesh=object(), factorize="on")
    with pytest.raises(ValueError, match="single-device"):
        run_spec_grid(y, x, masks, grid, procs=2, factorize="on")


def test_shared_center_matches_default_contraction_center():
    y, x, masks = _panel(seed=17)
    grid = _window_sweep_grid(y.shape[0], x.shape[2])
    universes, uidx, col_sel, window = _grid_tensors(grid, masks, y.shape[0])
    stats = contract_spec_grams(
        jnp.asarray(y), jnp.asarray(x), universes, jnp.asarray(uidx),
        jnp.asarray(col_sel), jnp.asarray(window),
    )
    np.testing.assert_allclose(
        np.asarray(stats.center),
        np.asarray(shared_center(jnp.asarray(x))), atol=0,
    )


def test_sharded_callers_must_pass_shared_center():
    y, x, masks = _panel(seed=19)
    grid = _window_sweep_grid(y.shape[0], x.shape[2])
    universes, uidx, col_sel, window = _grid_tensors(grid, masks, y.shape[0])
    with pytest.raises(ValueError, match="shard"):
        contract_spec_grams(
            jnp.asarray(y), jnp.asarray(x), universes, jnp.asarray(uidx),
            jnp.asarray(col_sel), jnp.asarray(window),
            expect_shared_center=True,
        )
