"""Overload-survival semantics: autoscaler, brownout ladder, crash-restart
recovery, journal retention, the retry-hint consumer, and the adversarial
load harness (ISSUE 12).

Layered on the PR-10 fleet contracts (tests/test_fleet.py pins those):
these tests assert only the NEW machinery —

- the supervisor's autoscaler leg grows the fleet under pressure
  (compile-free via the warm pool), shrinks it on sustained relief
  through drain-and-RETIRE (no replacement spawned), honors min/max and
  the cooldown deterministically under an injected clock, and serializes
  against rollover on the rollover lock;
- the brownout ladder steps full → coreset-m → shed only after scale-out
  is exhausted, stamps every degraded response with its route/precision
  disclosure (``DegradedQuote``), keeps the journal replay clean, and
  recovers hysteretically;
- ``ServingFleet.recover`` repairs a torn journal tail, closes out
  in-flight requests as typed retriable outcomes so the crashed session
  replays CLEAN (exactly-once across a process death), and rebuilds the
  fleet from the registry with zero fresh compiles at the journal's
  last-known topology;
- journal rotation retains the newest ``FMRP_FLEET_JOURNAL_KEEP``
  sessions with the drops disclosed;
- the load harness accounts every request to a typed outcome and the
  capacity model's prediction is self-consistent.
"""

import json
import threading
import time

import numpy as np
import pytest

from fm_returnprediction_tpu.resilience.errors import (
    RetryExhaustedError,
    ServiceOverloadError,
)
from fm_returnprediction_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    fleet_hard_crash,
    tear_journal_tail,
)
from fm_returnprediction_tpu.serving import (
    AdmissionPolicy,
    AutoscalePolicy,
    BrownoutPolicy,
    DegradedQuote,
    ERService,
    LoadGen,
    LoadPhase,
    RequestJournal,
    ServingFleet,
    build_serving_state,
    capacity_model,
    ingest_month,
    query_with_retry,
    replay_journal,
)
from fm_returnprediction_tpu.serving.brownout import (
    BrownoutController,
    degraded_project,
)
from fm_returnprediction_tpu.serving.recovery import repair_journal
from fm_returnprediction_tpu.serving.supervisor import DRAINING, HEALTHY

pytestmark = pytest.mark.fleet

T, N, P = 48, 40, 4
WINDOW, MIN_PERIODS = 16, 8


def _make_panel(seed=2016):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((T, N, P)).astype(np.float32)
    beta = (rng.standard_normal(P) * 0.05).astype(np.float32)
    y = (x @ beta + 0.02 * rng.standard_normal((T, N))).astype(np.float32)
    mask = rng.random((T, N)) > 0.1
    y = np.where(mask, y, np.nan).astype(np.float32)
    x = np.where(mask[..., None], x, np.nan).astype(np.float32)
    return y, x, mask


@pytest.fixture(scope="module")
def case():
    y, x, mask = _make_panel()
    state = build_serving_state(
        y, x, mask, window=WINDOW, min_periods=MIN_PERIODS
    )
    rng = np.random.default_rng(11)
    n_q = 100
    months = rng.integers(T // 2, T, n_q)
    firms = rng.integers(0, N, n_q)
    qx = x[months, firms]
    return y, x, mask, state, months, qx


# -- autoscaler ---------------------------------------------------------------


def test_scale_out_on_occupancy_pressure_and_scale_in_on_relief(case):
    """Queue pressure grows the fleet; sustained relief drains the
    youngest replica through DRAINING and RETIRES it — no replacement —
    with every transition journaled as a size-carrying topology mark."""
    _, _, _, state, months, qx = case
    clk = [1000.0]
    fleet = ServingFleet(
        state, 1, max_batch=8, max_queue=8, auto_flush=False,
        admission=AdmissionPolicy(max_occupancy=1.01),
        autoscale=AutoscalePolicy(
            min_replicas=1, max_replicas=2, cooldown_s=10.0,
            out_occupancy=0.5, in_occupancy=0.2, in_ticks=2,
        ),
        admission_clock=lambda: clk[0],
    )
    try:
        futs = [fleet.submit(int(months[k]), qx[k]) for k in range(6)]
        # 6/8 occupancy ≥ 0.5 → pressure → scale-out (cooldown anchor
        # allows the first action immediately)
        actions = fleet.supervisor.tick()
        assert any(a.startswith("scale-out:+1") for a in actions), actions
        assert fleet.stats()["healthy_replicas"] == 2
        assert fleet.stats()["scale_out_total"] == 1
        # at max: renewed pressure cannot grow further
        clk[0] += 11.0
        assert not any(
            a.startswith("scale-out") for a in fleet.supervisor.tick()
        )
        fleet.flush_all()
        for f in futs:
            f.result(timeout=5)
        # relief: two consecutive calm ticks (in_ticks=2) + cooldown
        clk[0] += 11.0
        assert not any(
            a.startswith("scale-in") for a in fleet.supervisor.tick()
        )
        actions = fleet.supervisor.tick()
        assert any(a.startswith("scale-in:") for a in actions), actions
        (draining,) = fleet.stats()["draining_replicas"]
        assert fleet.replica_states()[draining] == DRAINING
        # draining scale-in victim takes no new traffic and is RETIRED
        # once idle — fleet back to min, nothing spawned in its place
        actions = fleet.supervisor.tick()
        assert any(a == f"retire:{draining}" for a in actions), actions
        assert fleet.stats()["healthy_replicas"] == 1
        assert fleet.stats()["fleet_size"] == 1
        assert fleet.stats()["scale_in_total"] == 1
        assert draining in fleet.stats()["replaced"]
    finally:
        fleet.close()


def test_autoscale_cooldown_is_deterministic_under_injected_clock(case):
    _, _, _, state, months, qx = case
    clk = [0.0]
    fleet = ServingFleet(
        state, 1, max_batch=8, max_queue=4, auto_flush=False,
        admission=AdmissionPolicy(max_occupancy=1.01),
        autoscale=AutoscalePolicy(
            min_replicas=1, max_replicas=3, cooldown_s=30.0,
            out_occupancy=0.5,
        ),
        admission_clock=lambda: clk[0],
    )
    try:
        futs = [fleet.submit(int(months[k]), qx[k]) for k in range(3)]
        assert any(
            a.startswith("scale-out") for a in fleet.supervisor.tick()
        )
        # keep the pressure on: scale-out doubled the aggregate ceiling,
        # so refill past the threshold before probing the cooldown
        futs += [fleet.submit(int(months[k]), qx[k]) for k in range(3, 6)]
        # still hot, but inside the cooldown window: no second action
        assert not any(
            a.startswith("scale-out") for a in fleet.supervisor.tick()
        )
        clk[0] += 30.0
        assert any(
            a.startswith("scale-out") for a in fleet.supervisor.tick()
        )
        assert fleet.stats()["healthy_replicas"] == 3
        fleet.flush_all()
        for f in futs:
            f.result(timeout=5)
    finally:
        fleet.close()


def test_scale_out_spawns_compile_free_from_registry(case, tmp_path):
    """The elasticity claim that matters: a scale-out replica starts
    through the PR-9 warm pool with ZERO fresh compiles (WarmReport
    evidence), same as failover replacements."""
    from fm_returnprediction_tpu.registry.store import using_registry

    _, _, _, state, months, qx = case
    reg_dir = tmp_path / "registry"
    with using_registry(reg_dir):
        ERService(state, max_batch=8, auto_flush=False).close()
    fleet = ServingFleet(state, 1, max_batch=8, auto_flush=False,
                         registry_dir=reg_dir)
    try:
        (new_rid,) = fleet.scale_out(1, reason="test")
        report = fleet.warm_reports[new_rid]
        assert report.zero_compile, report
        assert report.fresh_compiles == 0
        f = fleet.submit(int(months[0]), qx[0], key="pin-to-anyone")
        fleet.flush_all()
        assert isinstance(f.result(timeout=5), float)
    finally:
        fleet.close()


def test_env_knobs_arm_autoscale_and_brownout(case, monkeypatch):
    _, _, _, state, *_ = case
    monkeypatch.setenv("FMRP_FLEET_MIN", "2")
    monkeypatch.setenv("FMRP_FLEET_MAX", "5")
    monkeypatch.setenv("FMRP_FLEET_COOLDOWN_S", "7.5")
    monkeypatch.setenv("FMRP_FLEET_BROWNOUT", "1")
    monkeypatch.setenv("FMRP_FLEET_BROWNOUT_M", "2")
    monkeypatch.setenv("FMRP_FLEET_BROWNOUT_LADDER", "full,bf16,shed")
    pol = AutoscalePolicy.from_env()
    assert pol is not None
    assert (pol.min_replicas, pol.max_replicas, pol.cooldown_s) == (2, 5, 7.5)
    with ServingFleet(state, 2, max_batch=8, auto_flush=False) as fleet:
        assert fleet.supervisor.autoscale == pol
        assert fleet.brownout is not None
        assert fleet.brownout.policy.coreset_m == 2
        assert fleet.brownout.policy.ladder == ("full", "bf16", "shed")
    monkeypatch.delenv("FMRP_FLEET_MIN")
    monkeypatch.delenv("FMRP_FLEET_MAX")
    monkeypatch.delenv("FMRP_FLEET_COOLDOWN_S")
    assert AutoscalePolicy.from_env() is None


def test_env_knob_edge_cases_cannot_crash_or_invert():
    """Misconfigured knobs reconcile or reject LOUDLY at policy
    construction — never as a crash at fleet start or a hard error on
    the degraded serving path."""
    # FMRP_FLEET_MIN alone above the default max: max follows up
    pol = AutoscalePolicy.from_env({"FMRP_FLEET_MIN": "8"})
    assert (pol.min_replicas, pol.max_replicas) == (8, 8)
    # FMRP_FLEET_MAX alone below the default min would be impossible too
    assert AutoscalePolicy.from_env({"FMRP_FLEET_MAX": "2"}).max_replicas == 2
    # ladder shape is enforced: 'full' only first, 'shed' only last
    with pytest.raises(ValueError, match="end at 'shed'"):
        BrownoutPolicy(ladder=("full", "shed", "coreset"))
    with pytest.raises(ValueError, match="interior rung"):
        BrownoutPolicy(ladder=("full", "shed", "coreset", "shed"))
    with pytest.raises(ValueError, match="end at 'shed'"):
        BrownoutPolicy(ladder=("full", "coreset"))
    with pytest.raises(ValueError, match="duplicate"):
        BrownoutPolicy(ladder=("full", "coreset", "coreset", "shed"))
    # a zero coreset cannot reach argpartition
    with pytest.raises(ValueError, match="coreset_m"):
        BrownoutPolicy(coreset_m=0)
    # BOTH bounds explicitly set and contradictory stays loud — silently
    # raising max would override an operator's capacity cap
    with pytest.raises(ValueError, match="contradictory"):
        AutoscalePolicy.from_env(
            {"FMRP_FLEET_MIN": "8", "FMRP_FLEET_MAX": "2"}
        )


def test_degraded_routes_bypass_occupancy_shedding(case):
    """The ladder must stay reachable when queues are pinned at the
    DEFAULT admission shed threshold: degraded answers never touch a
    queue, so occupancy shedding (0.9 default) must not preempt them —
    that would turn brownout back into the 429 it exists to avoid."""
    _, _, _, state, months, qx = case
    fleet = ServingFleet(
        state, 1, max_batch=8, max_queue=8, auto_flush=False,
        brownout=BrownoutPolicy(ladder=("full", "coreset", "shed"),
                                enter_burn=1e9, exit_burn=1.0,
                                enter_occupancy=0.5, exit_occupancy=0.1,
                                dwell_ticks=1, recover_ticks=2),
    )
    try:
        # pin the queue just under the default 0.9 ceiling, step the
        # ladder, then keep submitting: every new request must come back
        # degraded — not shed — while the queue stays pinned
        queued = [fleet.submit(int(months[k]), qx[k]) for k in range(7)]
        assert fleet.supervisor.tick() == ["brownout:coreset"]
        for k in range(7, 12):
            quote = fleet.query(int(months[k]), qx[k])
            assert isinstance(quote, DegradedQuote), k
        assert fleet._queue_snapshot()[0] == 7  # queue untouched
        assert fleet.stats()["shed_total"] == 0
        # and at the SHED rung the refusal is the ladder's own typed
        # brownout_shed — not the default occupancy shed firing first
        # and mislabeling the episode
        assert fleet.supervisor.tick() == ["brownout:shed"]
        with pytest.raises(ServiceOverloadError) as err:
            fleet.submit(int(months[0]), qx[0])
        assert err.value.reason == "brownout_shed"
        fleet.flush_all()
        for f in queued:
            f.result(timeout=5)
    finally:
        fleet.close()


def test_relief_scale_in_is_gated_while_brownout_active(case):
    """Under brownout the calm signals are artifacts (degraded requests
    bypass the queues): relief must NOT retire replicas until the ladder
    has fully recovered, or the fleet re-overloads the moment it does."""
    _, _, _, state, months, qx = case
    clk = [0.0]
    fleet = ServingFleet(
        state, 2, max_batch=8, max_queue=8, auto_flush=False,
        admission=AdmissionPolicy(max_occupancy=1.01),
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=2,
                                  cooldown_s=1.0, out_occupancy=0.5,
                                  in_occupancy=0.3, in_ticks=1),
        brownout=BrownoutPolicy(ladder=("full", "coreset", "shed"),
                                enter_burn=1e9, exit_burn=1.0,
                                enter_occupancy=0.5, exit_occupancy=0.1,
                                dwell_ticks=1, recover_ticks=10),
        admission_clock=lambda: clk[0],
    )
    try:
        queued = [fleet.submit(int(months[k]), qx[k]) for k in range(10)]
        assert fleet.supervisor.tick() == ["brownout:coreset"]
        fleet.flush_all()
        for f in queued:
            f.result(timeout=5)
        # calm by every queue signal, but the ladder is still engaged
        # (recover_ticks=10): in_ticks=1 relief must not fire
        for _ in range(4):
            clk[0] += 2.0
            actions = fleet.supervisor.tick()
            assert not any(a.startswith("scale-in") for a in actions), actions
        assert fleet.stats()["healthy_replicas"] == 2
        # ladder back at full → the same calm now counts as relief
        fleet.brownout.level = 0
        clk[0] += 2.0
        actions = fleet.supervisor.tick()
        assert any(a.startswith("scale-in") for a in actions), actions
    finally:
        fleet.close()


def test_scale_out_bounds_live_replicas_not_just_healthy(case):
    """max_replicas is a capacity cap on LIVE replicas: a draining
    replica plus a pressure scale-out must not overshoot it once the
    drained one is replaced."""
    _, _, _, state, months, qx = case
    fleet = ServingFleet(
        state, 2, max_batch=8, max_queue=8, auto_flush=False,
        admission=AdmissionPolicy(max_occupancy=1.01),
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=2,
                                  cooldown_s=0.0, out_occupancy=0.2),
    )
    try:
        victim = sorted(fleet.replica_states())[0]
        fleet.decommission(victim, reasons=["synthetic breach"])
        # pressure on the survivor: healthy=1 < max, but LIVE=2 == max
        futs = [fleet.submit(int(months[k]), qx[k]) for k in range(4)]
        actions = fleet.supervisor.tick()
        assert not any(a.startswith("scale-out") for a in actions), actions
        fleet.flush_all()
        for f in futs:
            f.result(timeout=5)
        # the drain completes through replace (not retire): still 2 live
        fleet.supervisor.tick()
        assert len(fleet.replica_states()) == 2
    finally:
        fleet.close()


# -- brownout ladder ----------------------------------------------------------


def test_brownout_controller_state_machine():
    """Pure ladder mechanics: pressure only steps down while scale-out is
    exhausted; recovery needs ``recover_ticks`` CONSECUTIVE calm ticks;
    the middle zone holds the rung and resets both streaks."""
    ctl = BrownoutController(BrownoutPolicy(
        ladder=("full", "coreset", "shed"),
        enter_burn=2.0, exit_burn=1.0,
        enter_occupancy=0.9, exit_occupancy=0.3,
        dwell_ticks=2, recover_ticks=2,
    ))
    hot = dict(burn=3.0, occupancy=0.0, scale_exhausted=True)
    calm = dict(burn=0.0, occupancy=0.0, scale_exhausted=True)
    mid = dict(burn=1.5, occupancy=0.0, scale_exhausted=True)
    # pressure while the autoscaler still has headroom: never steps
    assert ctl.update(burn=9.9, occupancy=1.0, scale_exhausted=False) is None
    assert ctl.level == 0
    assert ctl.update(**hot) is None          # dwell 1 of 2
    assert ctl.update(**hot) == "brownout:coreset"
    assert ctl.active_rung() == "coreset"
    assert ctl.update(**hot) is None          # dwell restarts per rung
    assert ctl.update(**hot) == "brownout:shed"
    assert ctl.level == 2
    assert ctl.update(**hot) is None          # floor: nowhere lower
    # recovery: consecutive calm ticks, broken streak restarts
    assert ctl.update(**calm) is None
    assert ctl.update(**mid) is None          # middle zone resets the streak
    assert ctl.update(**calm) is None
    assert ctl.update(**calm) == "recover:coreset"
    assert ctl.update(**calm) is None
    assert ctl.update(**calm) == "recover:full"
    assert not ctl.active


def test_brownout_ladder_end_to_end_disclosed_and_journal_clean(
        case, tmp_path):
    """The overload episode in miniature: queue pressure with scale-out
    exhausted steps the ladder to coreset (responses become
    ``DegradedQuote`` with route/m/err_bound disclosure, served without
    touching the saturated queues), then to shed (typed retriable 429),
    then drains → hysteretic recovery → plain floats again. The journal
    replays clean through all of it."""
    _, _, _, state, months, qx = case
    journal = tmp_path / "brownout.jsonl"
    fleet = ServingFleet(
        state, 2, max_batch=8, max_queue=8, auto_flush=False,
        admission=AdmissionPolicy(max_occupancy=1.01),
        journal=journal,
        brownout=BrownoutPolicy(
            ladder=("full", "coreset", "shed"),
            enter_burn=1e9, exit_burn=1.0,
            enter_occupancy=0.5, exit_occupancy=0.2,
            dwell_ticks=1, recover_ticks=2,
        ),
    )
    try:
        queued = [fleet.submit(int(months[k]), qx[k]) for k in range(10)]
        assert fleet.supervisor.tick() == ["brownout:coreset"]
        assert fleet.stats()["brownout_rung"] == "coreset"
        # degraded service: disclosed, host-side, queue depth UNCHANGED
        depth_before = fleet._queue_snapshot()[0]
        quote = fleet.query(int(months[0]), qx[0])
        assert isinstance(quote, DegradedQuote)
        assert quote.route == "coreset"
        assert quote.m == (P + 1) // 2
        assert quote.err_bound is not None and quote.err_bound >= 0
        assert fleet._queue_snapshot()[0] == depth_before
        assert fleet.stats()["degraded_total"] == 1
        # still under pressure → the last rung: shed with a typed 429
        assert fleet.supervisor.tick() == ["brownout:shed"]
        with pytest.raises(ServiceOverloadError) as err:
            fleet.submit(int(months[1]), qx[1])
        assert err.value.reason == "brownout_shed"
        assert err.value.retry_after_s > 0
        # drain the queues → relief → hysteretic recovery, one rung per
        # recover_ticks streak
        fleet.flush_all()
        for f in queued:
            f.result(timeout=5)
        assert fleet.supervisor.tick() == []
        assert fleet.supervisor.tick() == ["recover:coreset"]
        assert fleet.supervisor.tick() == []
        assert fleet.supervisor.tick() == ["recover:full"]
        full_fut = fleet.submit(int(months[0]), qx[0])
        fleet.flush_all()  # auto_flush off: pump the queued full-path query
        full = full_fut.result(timeout=5)
        assert not isinstance(full, DegradedQuote)
        # the degraded answer agrees with the full path within its own
        # disclosed error bound (plus f32 dust)
        assert (np.isnan(full) and np.isnan(quote)) or (
            abs(float(full) - float(quote))
            <= quote.err_bound + 1e-4 * (1 + abs(float(full)))
        )
    finally:
        fleet.close()
    replay = replay_journal(journal)
    assert replay.clean, (replay.dropped, replay.duplicated, replay.invalid)
    assert replay.n_shed == 1
    marks = [m["label"] for m in replay.marks]
    assert marks.count("brownout") == 4  # 2 down-steps + 2 recoveries


def test_degraded_projection_differentials(case):
    """coreset with m=P is the full formula (f32-exact to the kernel's
    answer); bf16 is the full formula at bf16 input rounding; both NaN
    exactly where the kernel is NaN."""
    _, _, _, state, months, qx = case
    with ERService(state, max_batch=8, auto_flush=False) as svc:
        futs = [svc.submit(int(m), q) for m, q in zip(months, qx)]
        svc.batcher.drain()
        full = np.asarray([f.result(timeout=5) for f in futs])
    for k in range(len(months)):
        idx = state.month_index(int(months[k]))
        everything = degraded_project(state, idx, qx[k], "coreset", m=P)
        bf16 = degraded_project(state, idx, qx[k], "bf16")
        half = degraded_project(state, idx, qx[k], "coreset", m=P // 2)
        if np.isnan(full[k]):
            assert np.isnan(everything) and np.isnan(bf16) and np.isnan(half)
            continue
        assert everything.m == P and everything.err_bound == 0.0
        np.testing.assert_allclose(float(everything), full[k],
                                   rtol=1e-5, atol=1e-6)
        # bf16 keeps ~8 mantissa bits per input; the dot of P terms stays
        # within a few parts in 1e2 of the f32 answer at these magnitudes
        np.testing.assert_allclose(float(bf16), full[k],
                                   rtol=0.05, atol=0.05)
        assert bf16.precision in ("bf16", "f16")
        if np.isfinite(half.err_bound):
            assert abs(float(half) - full[k]) <= half.err_bound + 1e-5


# -- crash-restart recovery ---------------------------------------------------


@pytest.mark.chaos
def test_hard_crash_recover_replays_clean_and_serves_again(case, tmp_path):
    """The acceptance scenario: the ``fleet.hard_crash`` site kills the
    fleet between two admits with requests still queued (in flight), the
    ``fleet.journal_torn_tail`` site tears the final journal line, and
    ``ServingFleet.recover`` (a) repairs the tail, (b) closes every
    in-flight request out to a typed retriable outcome so the crashed
    session replays CLEAN — zero dropped, zero duplicated — and (c)
    rebuilds the fleet from the registry at the journal's last-known
    topology with zero fresh compiles."""
    from fm_returnprediction_tpu.registry.store import using_registry

    _, _, _, state, months, qx = case
    reg_dir = tmp_path / "registry"
    with using_registry(reg_dir) as reg:
        from fm_returnprediction_tpu.registry import artifacts

        ERService(state, max_batch=8, auto_flush=False).close()
        artifacts.put_serving_state(state, "crash-test", registry=reg)
    journal = tmp_path / "crash.jsonl"
    fleet = ServingFleet(state, 2, max_batch=8, auto_flush=False,
                         registry_dir=reg_dir, journal=journal)
    fleet.scale_out(1, reason="pre-crash topology")  # last mark: size=3
    with FaultPlan({
        "fleet.hard_crash": FaultSpec(
            skip=12, times=1, mutate=fleet_hard_crash,
        ),
        "fleet.journal_torn_tail": FaultSpec(
            times=1, corrupt=tear_journal_tail,
        ),
    }) as plan:
        for k in range(20):
            try:
                fleet.submit(int(months[k]), qx[k])
            except Exception:  # noqa: BLE001 — post-crash submits fail
                pass
    assert plan.fired["fleet.hard_crash"] == 1
    assert plan.fired["fleet.journal_torn_tail"] == 1
    # the crashed session on disk is dirty: torn tail + dangling admits
    dirty = replay_journal(journal)
    assert not dirty.clean
    # --- the "next process" ---
    recovered, report = ServingFleet.recover(
        journal, registry_dir=reg_dir, max_batch=8, auto_flush=False,
    )
    try:
        assert report.journal.torn_lines == 1
        assert len(report.journal.recovered) >= 12  # the queued in-flight
        assert all(r.last_event in ("admit", "route", "requeue")
                   for r in report.journal.recovered)
        assert report.clean and report.journal.replay_clean
        # topology from the journal's size-carrying marks
        assert report.n_replicas == 3
        assert report.state_source == f"registry:{reg_dir}"
        # warm pool: every recovered replica started compile-free
        assert report.zero_compile_starts == 3
        # the recovered session was rotated and replays clean standalone
        assert report.rotated_to is not None
        rotated = replay_journal(report.rotated_to)
        assert rotated.clean, (rotated.dropped, rotated.invalid)
        assert len(rotated.dropped) == 0 and len(rotated.duplicated) == 0
        assert report.rotated_to.name in report.prior_sessions
        # and it serves
        f = recovered.submit(int(months[0]), qx[0])
        recovered.flush_all()
        assert isinstance(f.result(timeout=5), float)
    finally:
        recovered.close()
    final = replay_journal(journal)
    assert final.clean


@pytest.mark.chaos
def test_repair_journal_truncates_only_the_torn_tail(tmp_path):
    path = tmp_path / "j.jsonl"
    lines = [
        {"seq": 1, "ev": "admit", "req": 1},
        {"seq": 2, "ev": "route", "req": 1, "replica": "r0"},
        {"seq": 3, "ev": "done", "req": 1},
    ]
    with open(path, "w") as fh:
        for rec in lines:
            fh.write(json.dumps(rec) + "\n")
        fh.write('{"seq": 4, "ev": "adm')  # torn mid-append
    dropped_lines, dropped_bytes = repair_journal(path)
    assert dropped_lines == 1 and dropped_bytes > 0
    replay = replay_journal(path)
    assert replay.clean and replay.n_done == 1
    # idempotent: a clean file is untouched
    assert repair_journal(path) == (0, 0)
    # a complete final record missing only its "\n" is SOUND — no torn
    # lines, never a negative byte count — but the newline is restored
    # so a later close-out append cannot concatenate onto the record
    raw = path.read_bytes().rstrip(b"\n")
    path.write_bytes(raw)
    assert repair_journal(path) == (0, 0)
    assert path.read_bytes() == raw + b"\n"
    assert replay_journal(path).clean


@pytest.mark.chaos
def test_recover_newline_cut_with_dangling_request(case, tmp_path):
    """The crash shape that bites hardest: the final line is complete
    JSON but its newline was cut, AND a request is still in flight —
    close-out must append on a FRESH line, not concatenate onto (and
    destroy) the last real event."""
    from fm_returnprediction_tpu.serving.recovery import recover_journal

    path = tmp_path / "cut.jsonl"
    lines = [
        {"seq": 1, "ev": "admit", "req": 1},
        {"seq": 2, "ev": "route", "req": 1, "replica": "r0"},
        {"seq": 3, "ev": "done", "req": 1},
        {"seq": 4, "ev": "admit", "req": 2},  # in flight at the crash
    ]
    payload = "\n".join(json.dumps(rec) for rec in lines)  # no final \n
    path.write_text(payload)
    jrec = recover_journal(path)
    assert jrec.torn_lines == 0
    assert [r.req for r in jrec.recovered] == [2]
    assert jrec.replay_clean, jrec
    replay = replay_journal(path)
    assert replay.n_done == 1 and replay.n_error == 1  # seq-3 done SURVIVED


def test_recover_requires_a_state_source(tmp_path):
    journal = tmp_path / "j.jsonl"
    with RequestJournal(journal) as j:
        j.append("admit", 1)
        j.append("shed", 1)
    with pytest.raises(ValueError, match="registry"):
        ServingFleet.recover(journal)


def test_recover_with_explicit_state_closes_out_in_flight(case, tmp_path):
    """No registry: an explicit state still recovers, and a request that
    was admitted-but-unrouted at the crash is closed out retriable."""
    _, _, _, state, *_ = case
    journal = tmp_path / "j.jsonl"
    with RequestJournal(journal) as j:
        j.mark("fleet_start", size=1)
        j.append("admit", 1)
        j.append("route", 1, replica="r0")
        j.append("done", 1)
        j.append("admit", 2)   # in flight forever: the process died
    fleet, report = ServingFleet.recover(
        journal, state=state, max_batch=8, auto_flush=False,
    )
    try:
        assert report.state_source == "explicit"
        assert [r.req for r in report.journal.recovered] == [2]
        assert report.journal.recovered[0].last_event == "admit"
        assert report.clean
        assert report.n_replicas == 1
    finally:
        fleet.close()


# -- journal retention (satellite) -------------------------------------------


def test_journal_retention_keeps_newest_and_discloses_drops(tmp_path):
    path = tmp_path / "j.jsonl"
    for session in range(4):
        with RequestJournal(path, keep=2) as j:
            j.append("admit", 1)
            j.append("route", 1, replica="r0", session=session)
            j.append("done", 1)
    # after 4 sessions: live file + the newest 2 rotations (.2, .3);
    # .1 was dropped at the last rotation and disclosed
    with RequestJournal(path, keep=2) as j:
        assert j.rotated_to == path.with_name("j.jsonl.4")
        kept = sorted(p.name for _, p in j.sessions())
        assert kept == ["j.jsonl.3", "j.jsonl.4"]
        assert [p.name for p in j.dropped_sessions] == ["j.jsonl.2"]
    replay = replay_journal(path.with_name("j.jsonl.4"))
    assert replay.clean
    # keep=0 keeps everything
    for _ in range(3):
        with RequestJournal(path, keep=0) as j:
            j.append("shed", 1)
    assert len(RequestJournal(path, keep=0).sessions()) >= 5


def test_journal_retention_mark_is_visible_in_replay(tmp_path):
    path = tmp_path / "j.jsonl"
    for _ in range(3):
        with RequestJournal(path, keep=1) as j:
            j.append("shed", 1)
    labels = [m["label"] for m in replay_journal(path).marks]
    assert "journal_retention" in labels


# -- the retry-hint consumer (satellite) -------------------------------------


def test_query_with_retry_consumes_retry_after_hint(case):
    """The shared client helper: a token-bucket shed's ``retry_after_s``
    becomes the backoff FLOOR — sleeping the hint (injected sleep drives
    the injected clock) admits the retry; the caller sees the answer,
    not the 429."""
    _, _, _, state, months, qx = case
    clk = [0.0]
    slept = []

    def fake_sleep(s):
        slept.append(s)
        clk[0] += s

    fleet = ServingFleet(
        state, 1, max_batch=8, auto_flush=True,
        admission=AdmissionPolicy(rate_per_s=10.0, burst=1.0),
        admission_clock=lambda: clk[0],
    )
    try:
        first = query_with_retry(fleet, int(months[0]), qx[0],
                                 sleep=fake_sleep)
        assert isinstance(first, float) and not slept
        # bucket empty: the next query sheds once, sleeps ≥ the hint
        # (0.1 s at 10 req/s — far above the policy's 5 ms first backoff),
        # then succeeds on the retry
        second = query_with_retry(fleet, int(months[1]), qx[1],
                                  sleep=fake_sleep)
        assert isinstance(second, float)
        assert len(slept) == 1 and slept[0] >= 0.1 - 1e-9
        assert fleet.stats()["shed_total"] == 1
    finally:
        fleet.close()


def test_query_with_retry_exhausts_with_last_429_as_cause(case):
    _, _, _, state, months, qx = case
    from fm_returnprediction_tpu.resilience.retry import RetryPolicy

    clk = [0.0]
    fleet = ServingFleet(
        state, 1, max_batch=8, auto_flush=False,
        admission=AdmissionPolicy(rate_per_s=0.001, burst=1.0),
        admission_clock=lambda: clk[0],
    )
    try:
        fleet.submit(int(months[0]), qx[0])  # drains the burst
        with pytest.raises(RetryExhaustedError) as err:
            query_with_retry(
                fleet, int(months[1]), qx[1],
                policy=RetryPolicy(
                    max_attempts=2, backoff_s=0.001,
                    retry_on=(ServiceOverloadError,),
                ),
                sleep=lambda s: None,
            )
        assert isinstance(err.value.__cause__, ServiceOverloadError)
        fleet.flush_all()
    finally:
        fleet.close()


# -- supervisor concurrency (satellite) --------------------------------------


def test_tick_failover_serializes_with_rollover_lock(case):
    """``tick()``'s failover (replace) racing ``rollover()``: the stalled
    PREPARE (``fleet.poison_state`` delay) holds the rollover lock, the
    concurrent tick's replacement must WAIT it out and then spawn from
    the NEW version — the fleet can never split across versions."""
    y, x, mask, state, months, qx = case
    new_state = ingest_month(
        state, y[-1], x[-1], mask[-1], np.datetime64("2031-01-31", "ns")
    )
    fleet = ServingFleet(state, 2, max_batch=8, auto_flush=False)
    try:
        victim = sorted(fleet.replica_states())[0]
        fleet.kill_replica(victim, reason="pre-rollover corpse")
        started = threading.Event()
        done = {}

        def roll():
            with FaultPlan({
                "fleet.poison_state": FaultSpec(times=-1, delay_s=0.4),
            }):
                started.set()
                done["version"] = fleet.rollover(new_state)

        th = threading.Thread(target=roll)
        th.start()
        started.wait(timeout=5)
        time.sleep(0.05)  # let PREPARE take the rollover lock and stall
        t0 = time.perf_counter()
        actions = fleet.supervisor.tick()   # wants to failover the corpse
        waited = time.perf_counter() - t0
        th.join(timeout=10)
        assert done.get("version") == 1
        assert any(a.startswith("failover:") for a in actions)
        assert waited >= 0.1, "tick did not serialize against rollover"
        # every live replica — including the mid-rollover replacement —
        # serves the committed version
        for rid in fleet.replica_states():
            assert fleet.replica(rid).service.state is fleet.state
        assert fleet.state is new_state
    finally:
        fleet.close()


def test_autoscale_mid_rollover_serializes_and_spawns_new_version(case):
    """``scale_out`` racing ``rollover()`` on the rollover lock: the
    autoscaler's spawn waits out the stalled PREPARE and reads the
    committed state — not the one being replaced."""
    y, x, mask, state, months, qx = case
    new_state = ingest_month(
        state, y[-1], x[-1], mask[-1], np.datetime64("2031-01-31", "ns")
    )
    fleet = ServingFleet(state, 1, max_batch=8, auto_flush=False)
    try:
        started = threading.Event()

        def roll():
            with FaultPlan({
                "fleet.poison_state": FaultSpec(times=-1, delay_s=0.4),
            }):
                started.set()
                fleet.rollover(new_state)

        th = threading.Thread(target=roll)
        th.start()
        started.wait(timeout=5)
        time.sleep(0.05)
        (rid,) = fleet.scale_out(1, reason="race")
        th.join(timeout=10)
        assert fleet.version == 1
        assert fleet.replica(rid).service.state is new_state
        for r in fleet.replica_states():
            assert fleet.replica(r).service.state is new_state
    finally:
        fleet.close()


# -- load harness -------------------------------------------------------------


def test_loadgen_accounts_every_request_with_typed_outcomes(case, tmp_path):
    """Burst + hot-key + poison adversarial mix: every request lands in
    exactly one outcome bucket, poison rows fail alone (the fleet keeps
    serving), and the journal replays clean."""
    _, _, _, state, months, qx = case
    journal = tmp_path / "load.jsonl"
    fleet = ServingFleet(state, 2, max_batch=16, max_latency_ms=1.0,
                         journal=journal)
    try:
        gen = LoadGen(fleet, months, qx, seed=7)
        report = gen.run([
            LoadPhase("burst", n_requests=60, workers=4),
            LoadPhase("hot", n_requests=40, workers=4, hot_key_frac=0.8),
            LoadPhase("poison", n_requests=40, workers=4, poison_frac=0.25),
        ])
        assert report["n"] == 140
        for phase in report["phases"]:
            buckets = (phase["ok"] + phase["degraded"] + phase["shed"]
                       + phase["poison_rejected"] + phase["errors"])
            assert buckets == phase["n"], phase
            assert phase["rows_per_s"] is None or phase["rows_per_s"] > 0
        poison_phase = report["phases"][2]
        assert poison_phase["poison_rejected"] > 0
        assert poison_phase["errors"] == 0
        assert poison_phase["ok"] > 0  # clean rows unharmed by poison ones
        fleet.drain(timeout=10)
    finally:
        fleet.close()
    replay = replay_journal(journal)
    assert replay.clean, (replay.dropped, replay.duplicated, replay.invalid)


def test_loadgen_ramp_schedule_is_deterministic_and_rising(case):
    _, _, _, state, months, qx = case
    fleet = ServingFleet(state, 1, max_batch=8, auto_flush=False)
    try:
        gen = LoadGen(fleet, months, qx, seed=3)
        phase = LoadPhase("ramp", n_requests=50, rate_per_s=1000.0,
                          ramp=True)
        sched = gen._schedule(phase, t0=0.0)
        assert sched is not None and len(sched) == 50
        gaps = np.diff(sched)
        assert (gaps >= 0).all()
        # sqrt profile: the back half arrives faster than the front half
        assert gaps[: len(gaps) // 2].mean() > gaps[len(gaps) // 2:].mean()
        again = gen._schedule(phase, t0=0.0)
        np.testing.assert_array_equal(sched, again)
    finally:
        fleet.close()


def test_coreset_bound_zero_slope_against_unbounded_support():
    """A dropped zero-slope column contributes exactly 0 to the error
    bound even when its support is unbounded — 0·inf must not poison
    the month with NaN (or warn)."""
    from fm_returnprediction_tpu.serving.brownout import _keep_and_bound

    with np.errstate(invalid="raise"):
        keep, bound = _keep_and_bound(
            slopes=np.array([[0.5, 0.0]]),
            x_lo=np.array([[-1.0, -np.inf]]),
            x_hi=np.array([[1.0, np.inf]]),
            m=1,
        )
    assert keep[0].tolist() == [True, False]
    assert bound[0] == 0.0
    # a WEIGHTED dropped column against unbounded support stays an
    # honest inf disclosure
    _, bound = _keep_and_bound(
        slopes=np.array([[0.5, 0.2]]),
        x_lo=np.array([[-1.0, -np.inf]]),
        x_hi=np.array([[1.0, np.inf]]),
        m=1,
    )
    assert np.isinf(bound[0])


def test_loadgen_second_run_reports_only_its_own_traffic(case):
    _, _, _, state, months, qx = case
    fleet = ServingFleet(state, 1, max_batch=8, max_latency_ms=1.0)
    try:
        gen = LoadGen(fleet, months, qx, seed=9)
        first = gen.run([LoadPhase("a", n_requests=10, workers=2)])
        second = gen.run([LoadPhase("b", n_requests=6, workers=2)])
        assert first["n"] == 10 and second["n"] == 6
        assert [p["phase"] for p in second["phases"]] == ["b"]
        assert len(gen.phase_reports) == 2  # all-time history retained
        fleet.drain(timeout=10)
    finally:
        fleet.close()


def test_capacity_model_predicts_and_validates(case):
    """The capacity model's prediction is positive, carries its inputs,
    and a measured closed-loop burst lands within an order of magnitude
    of it (the bench tracks the exact ratio; here we pin sanity, not the
    box's speed)."""
    _, _, _, state, months, qx = case
    fleet = ServingFleet(state, 2, max_batch=16, max_latency_ms=1.0)
    try:
        model = capacity_model(fleet)
        assert model["predicted_rows_per_s"] > 0
        assert model["healthy_replicas"] == 2
        assert model["bucket"] == 16
        assert model["dispatch_s"] > 0
        gen = LoadGen(fleet, months, qx, seed=5)
        report = gen.run([LoadPhase("probe", n_requests=80, workers=8)])
        measured = report["phases"][0]["rows_per_s"]
        assert measured is not None and measured > 0
        # the model is a ceiling estimate; measured should not EXCEED it
        # by more than dispatch-overlap slack
        assert measured <= model["predicted_rows_per_s"] * 10
        fleet.drain(timeout=10)
    finally:
        fleet.close()
