"""One topology controller (ISSUE 19).

Evidence in five layers, cheapest first:

- the SPEC: declarative shape round-trips through the journal's
  ``topology`` mark and the ``FMRP_TOPO_*`` env, invalid shapes are
  typed rejections;
- CROSS-PROCESS CHAOS: a parent ``FaultPlan`` rides ``FMRP_CHAOS_*``
  env into spawned children, proc-targeted so a pool-wide env kills
  exactly one member, with 30/30 deterministic trigger decisions;
- the SEAMS: a writer dying at the shm commit seam leaves a frame the
  reader NEVER observes (30/30), abandoned segments/doorbells are
  reclaimed and counted by the hygiene sweep, the broker connect path
  retries through a late listener and exhausts as a TYPED error, and
  the fan-out-before-rank-0 ordering survives 30 consecutive rounds;
- the CONTROLLER: killed / hung / ring_stalled are classified
  DISTINCTLY on real OS processes, repair respawns compile-free from
  the warm pool, SIGKILL-mid-result-send is exactly-once on BOTH
  transports, and ANY declared shape {thread, proc+shm, proc+socket,
  mixed+grid} rebuilds from the journal alone with clean replay;
- the GRID: a dead worker degrades to a DISCLOSED N-1 partial sum
  (exact by Gram additivity, refusable by knob), a chaos-killed rank
  does the same from INSIDE the child, and a broker death mid-round is
  re-elected with the round fanned out again, bit-identically.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from fm_returnprediction_tpu.parallel.shm import shm_available
from fm_returnprediction_tpu.resilience import (
    DegradedWorldError,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    chaos_env,
    install_plan_from_env,
)
from fm_returnprediction_tpu.topology import (
    Member,
    TopologyController,
    TopologySpec,
)

pytestmark = [pytest.mark.topology]

_SHM = pytest.mark.skipif(not shm_available(),
                          reason="POSIX shared memory unavailable here")


# -- the declarative spec ----------------------------------------------------


def test_spec_mark_roundtrip_counts_and_env(monkeypatch):
    spec = TopologySpec(replicas=3, replica_mode="process",
                        transport="shm", grid_procs=2,
                        grid_transport="frames")
    assert TopologySpec.from_mark(spec.to_mark()) == spec
    assert json.loads(json.dumps(spec.to_mark())) == spec.to_mark()
    assert spec.counts() == {"router": 1, "replica_process": 3,
                             "grid_worker": 2, "broker": 1}
    # no grid → no embedded broker in the inventory
    assert TopologySpec(replicas=1).counts()["broker"] == 0
    monkeypatch.setenv("FMRP_TOPO_REPLICAS", "4")
    monkeypatch.setenv("FMRP_TOPO_REPLICA_MODE", "process")
    monkeypatch.setenv("FMRP_TOPO_TRANSPORT", "socket")
    monkeypatch.setenv("FMRP_TOPO_GRID_PROCS", "3")
    assert TopologySpec.from_env() == TopologySpec(
        replicas=4, replica_mode="process", transport="socket",
        grid_procs=3)


def test_spec_validation_is_typed():
    with pytest.raises(ValueError, match="at least one replica"):
        TopologySpec(replicas=0)
    with pytest.raises(ValueError, match="replica_mode"):
        TopologySpec(replica_mode="fiber")
    with pytest.raises(ValueError, match="transport"):
        TopologySpec(transport="carrier-pigeon",
                     replica_mode="process")
    with pytest.raises(ValueError, match="process replicas"):
        TopologySpec(replica_mode="thread", transport="shm")
    with pytest.raises(ValueError, match="grid_transport"):
        TopologySpec(grid_procs=2, grid_transport="nfs")


# -- cross-process chaos propagation -----------------------------------------


def test_chaos_env_rides_to_the_right_child_only():
    """A pool-wide ``FMRP_CHAOS_*`` env installs in EXACTLY the child
    whose process identity matches the spec's ``proc`` — the primitive
    every one-member-of-N death in this file rides."""
    plan = FaultPlan({
        "grid.rank_death": FaultSpec(times=1, sigkill=True, proc="2"),
        "replica.verb": FaultSpec(times=2, delay_s=0.1),
        # a live callable cannot ride env and must be SKIPPED whole,
        # never half-shipped
        "parent.only": FaultSpec(mutate=lambda p: p),
    }, seed=7)
    with plan:
        env = chaos_env()
    wire = json.loads(env["FMRP_CHAOS_PLAN"])
    assert set(wire) == {"grid.rank_death", "replica.verb"}
    assert env["FMRP_CHAOS_SEED"] == "7"
    # the targeted child gets the bomb...
    child = {**env, "FMRP_DIST_PROC_ID": "2"}
    got = install_plan_from_env(child)
    assert got is not None and got.specs["grid.rank_death"].sigkill
    got.__exit__(None, None, None)  # don't leak into later tests
    # ...every other child drops it and keeps only untargeted specs
    other = {**env, "FMRP_DIST_PROC_ID": "1"}
    got = install_plan_from_env(other)
    assert got is not None and set(got.specs) == {"replica.verb"}
    got.__exit__(None, None, None)
    # no plan active → empty env → no-op install
    assert chaos_env() == {} and install_plan_from_env({}) is None


def test_chaos_trigger_decisions_are_deterministic_30x():
    """The same (seed, site, call_no) must decide the same way on every
    run — parent and env-rebuilt child plans fire IDENTICALLY, which is
    what makes the whole campaign repeatable 30/30."""
    spec = FaultSpec(probability=0.4, times=-1)
    with FaultPlan({"s": spec}, seed=13) as plan:
        env = chaos_env()
    baseline = [plan._should_fire(spec, n, "s") for n in range(1, 31)]
    assert 0 < sum(baseline) < 30  # the seed actually splits both ways
    for _ in range(30):
        rebuilt = install_plan_from_env({**env, "FMRP_DIST_PROC_ID": "1"})
        got = [rebuilt._should_fire(rebuilt.specs["s"], n, "s")
               for n in range(1, 31)]
        rebuilt.__exit__(None, None, None)
        assert got == baseline


# -- the commit seam: torn frames read as absent, 30/30 ----------------------


@_SHM
def test_writer_death_at_commit_seam_leaves_no_frame_30x():
    """A writer dying BETWEEN the payload/length stores and the commit
    word (the ``shm.ring.commit`` site — where a SIGKILL mid-send
    lands) must leave a frame the reader never observes; after healing,
    the NEXT send reuses the seat cleanly. 30 consecutive rounds."""
    from fm_returnprediction_tpu.parallel.shm import ShmRing, attach_ring

    ring = ShmRing(create=True, slots=4, slot_bytes=256)
    try:
        reader = attach_ring(ring.name)
        for i in range(30):
            with FaultPlan({"shm.ring.commit": FaultSpec(times=1)}) as p:
                with pytest.raises(InjectedFault):
                    ring.send(f"torn-{i}".encode(), timeout_s=1.0)
                assert p.fired["shm.ring.commit"] == 1
            # the torn frame is ABSENT, not garbage
            assert reader.recv(timeout_s=0.05) is None
            ring.send(f"clean-{i}".encode(), timeout_s=1.0)
            assert reader.recv(timeout_s=1.0) == f"clean-{i}".encode()
        reader.close()
    finally:
        ring.close()


# -- fd/segment hygiene ------------------------------------------------------


@_SHM
def test_sweep_reclaims_abandoned_segments_and_doorbells():
    """Segments and doorbell fds abandoned without close (an abnormal
    exit) are reclaimed by the controller sweep and COUNTED as leaks;
    a second sweep finds nothing — and a clean close leaks nothing."""
    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.parallel import shm as pshm
    from fm_returnprediction_tpu.serving import shm as sshm

    # drain anything earlier tests abandoned so the counts are ours
    pshm.sweep_segments()
    sshm.sweep_doorbells()
    seg_ctr = telemetry.registry().counter(
        "fmrp_topology_leaked_segments_total")
    before = seg_ctr.value
    ring = pshm.ShmRing(create=True, slots=4, slot_bytes=128)
    bell = sshm._make_doorbell()
    leaked = TopologyController.sweep(None)  # static in behavior
    assert ring.name in leaked["segments"]
    assert seg_ctr.value == before + 1
    if bell is not None:  # eventfd-less hosts have no bell to leak
        assert bell in leaked["fds"]
        with pytest.raises(OSError):
            os.fstat(bell)  # the fd is actually CLOSED, not just counted
    assert TopologyController.sweep(None) == {"segments": [], "fds": []}
    # clean lifecycle → zero leaks
    ring2 = pshm.ShmRing(create=True, slots=4, slot_bytes=128)
    ring2.close()
    assert TopologyController.sweep(None)["segments"] == []


# -- broker connect hardening ------------------------------------------------


def _cfg(port, world, rank):
    from fm_returnprediction_tpu.parallel.distributed import DistConfig

    return DistConfig(coordinator=f"127.0.0.1:{port}",
                      num_processes=world, process_id=rank)


def test_connect_retries_through_a_late_listener():
    """The cold-start shape: a rank that dials BEFORE the broker binds
    must join via deterministic backoff, not crash on the first
    ECONNREFUSED."""
    from fm_returnprediction_tpu.parallel.distributed import (
        HostExchange,
        free_port,
    )

    port = free_port()
    out = {}

    def late_rank1():
        ex = HostExchange(_cfg(port, 2, 1), timeout_s=30.0)
        try:
            out[1] = ex.allgather_obj("r1")
        finally:
            ex.close()

    t = threading.Thread(target=late_rank1)
    t.start()          # dials a port NOBODY listens on yet
    time.sleep(0.4)    # several refused attempts happen in this window
    ex0 = HostExchange(_cfg(port, 2, 0), timeout_s=30.0)
    try:
        assert ex0.allgather_obj("r0") == ["r0", "r1"]
    finally:
        t.join(timeout=30)
        ex0.close()
    assert out[1] == ["r0", "r1"]


def test_connect_exhaustion_is_typed_with_retry_evidence():
    from fm_returnprediction_tpu.parallel.distributed import (
        DistributedError,
        HostExchange,
        free_port,
    )
    from fm_returnprediction_tpu.resilience.errors import (
        RetryExhaustedError,
    )

    port = free_port()  # reserved by nobody: every dial is refused
    with pytest.raises(DistributedError, match="could not join") as ei:
        HostExchange(_cfg(port, 2, 1), timeout_s=0.5)
    assert isinstance(ei.value.__cause__, RetryExhaustedError)


def test_broker_fans_out_before_answering_rank0_30x():
    """30 consecutive in-thread rounds through the real broker: the
    rank-0-last fan-out ordering (PR 18) must hold up under repetition
    — any regression shows as a hang or a skewed round, not luck."""
    from fm_returnprediction_tpu.parallel.distributed import (
        HostExchange,
        free_port,
    )

    port = free_port()
    world, rounds = 3, 30
    got = {}

    def rank(r):
        ex = HostExchange(_cfg(port, world, r), timeout_s=60.0)
        try:
            acc = []
            for k in range(rounds):
                acc.append(ex.allgather_obj((r, k)))
            got[r] = acc
        finally:
            ex.close()

    threads = [threading.Thread(target=rank, args=(r,))
               for r in range(1, world)]
    for t in threads:
        t.start()
    rank(0)
    for t in threads:
        t.join(timeout=60)
    expect = [[(r, k) for r in range(world)] for k in range(rounds)]
    assert got == {r: expect for r in range(world)}


# -- the controller on real OS processes -------------------------------------


def _tiny_state(rng, t=36, n=60, p=4):
    from fm_returnprediction_tpu.serving import build_serving_state

    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = (rng.standard_normal(p) * 0.05).astype(np.float32)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(y, x, mask, window=18, min_periods=9)
    months = np.nonzero(state.have_coef())[0]
    return state, months


def _probe_until(ctl, rid, want, budget_s=10.0):
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        probe = ctl.probe()
        if probe.get(rid) == want:
            return probe
        time.sleep(0.05)
    pytest.fail(f"{rid} never classified {want!r}: {ctl.probe()}")


@_SHM
@pytest.mark.timeout(420)
def test_probe_ladder_classifies_killed_hung_ring_stalled(tmp_path):
    """The classification ladder on REAL processes: a SIGSTOPped child
    with a clean ring is ``hung`` (ping timeout), the same child with a
    frozen req-ring backlog is ``ring_stalled`` (watermark two-sample),
    a SIGKILLed child is ``killed`` — three DISTINCT verdicts, each
    repaired by a warm respawn with a journaled ``respawn`` mark."""
    from fm_returnprediction_tpu.serving import ServingFleet

    rng = np.random.default_rng(3)
    state, months = _tiny_state(rng)
    journal = tmp_path / "journal.jsonl"
    spec = TopologySpec(replicas=2, replica_mode="process",
                        transport="shm")
    fleet = ServingFleet(state, 2, replica_mode="process",
                         transport="shm", journal=str(journal),
                         registry_dir=str(tmp_path / "registry"),
                         max_batch=16, max_latency_ms=2.0)
    ctl = TopologyController(spec, fleet=fleet, ping_timeout_s=0.5)
    try:
        assert all(v == "live" for v in ctl.probe().values())
        kinds = sorted(m.kind for m in ctl.members())
        assert kinds == ["replica_process", "replica_process", "router"]

        # hung: alive pid, clean ring, no ping answer
        victim = sorted(fleet.replica_states())[0]
        svc = fleet.replica(victim).service
        os.kill(svc.pid, signal.SIGSTOP)
        probe = _probe_until(ctl, victim, "hung")

        # ring_stalled: same corpse-to-be, now with a frozen backlog —
        # the ladder must STOP calling it hung (distinct verdicts)
        svc._channel.req_ring.send(b"backlog", timeout_s=1.0)
        probe = _probe_until(ctl, victim, "ring_stalled")

        # repair: SIGKILL-on-stopped works, replacement is warm
        actions = ctl.repair(probe)
        assert actions and actions[0].startswith(f"respawn:{victim}")
        assert ctl.repair() == []  # converged: nothing left to fix
        probe = ctl.probe()
        assert sorted(probe.values()) == ["live", "live"], probe

        # killed: the replacement's peer, SIGKILLed outright
        victim2 = sorted(fleet.replica_states())[0]
        pid2 = fleet.replica(victim2).service.pid
        os.kill(pid2, signal.SIGKILL)
        probe = _probe_until(ctl, victim2, "killed")
        (action,) = ctl.repair(probe)
        new_rid = action.split("->")[1].split(":")[0]
        assert fleet.warm_reports[new_rid].fresh_compiles == 0
        assert sorted(ctl.probe().values()) == ["live", "live"]

        # the topology still serves, and the journal tells the story
        assert np.isfinite(fleet.query(int(months[0]),
                                       np.zeros(4, np.float32)))
        marks = [json.loads(ln) for ln in
                 journal.read_text().splitlines() if ln.strip()]
        labels = [m.get("label") for m in marks if m.get("ev") == "mark"]
        assert labels.count("respawn") == 2
        assert "topology" in labels
    finally:
        ctl.close()
    assert ctl.sweep() == {"segments": [], "fds": []}


@_SHM
@pytest.mark.timeout(420)
@pytest.mark.parametrize("transport", ["shm", "socket"])
def test_sigkill_mid_result_send_is_exactly_once(tmp_path, transport):
    """THE seam pin, both transports: chaos env makes replica 0 SIGKILL
    ITSELF mid-result-send (a real cross-process no-cleanup death at
    the worst moment). The in-flight request lands exactly once via the
    survivor, the journal replays CLEAN, and the controller's respawn
    quotes bit-identically with ZERO fresh compiles."""
    from fm_returnprediction_tpu.serving import ServingFleet, replay_journal

    rng = np.random.default_rng(5)
    state, months = _tiny_state(rng)
    journal = tmp_path / "journal.jsonl"
    reg_dir = tmp_path / "registry"
    spec = TopologySpec(replicas=2, replica_mode="process",
                        transport=transport)
    # the seam differs per transport: socket results leave through the
    # replica.result_send site; shm results leave through a ring commit
    # (the commit-last protocol is the torn-frame guarantee under test)
    site = ("replica.result_send" if transport == "socket"
            else "shm.ring.commit")
    # the bomb rides FMRP_CHAOS_* env into child 0 ONLY, armed while
    # the fleet spawns, disarmed in the parent before any repair
    with FaultPlan({site:
                    FaultSpec(times=1, sigkill=True, proc="0")}):
        fleet = ServingFleet(state, 2, replica_mode="process",
                             transport=transport, journal=str(journal),
                             registry_dir=str(reg_dir),
                             max_batch=16, max_latency_ms=2.0)
    ctl = TopologyController(spec, fleet=fleet, ping_timeout_s=1.0)
    try:
        qx = rng.standard_normal(4).astype(np.float32)
        month = int(months[0])
        # fan enough submits that BOTH replicas send results: replica 0
        # dies mid-send, the router requeues its casualties
        futs = [fleet.submit(month, qx) for _ in range(8)]
        vals = [f.result(timeout=60) for f in futs]
        assert len(set(vals)) == 1 and np.isfinite(vals[0])
        # the corpse is classified and respawned COMPILE-FREE
        dead = [r for r, s in ctl.probe().items() if s != "live"]
        assert len(dead) == 1, dead
        (action,) = ctl.repair()
        new_rid = action.split("->")[1].split(":")[0]
        assert fleet.warm_reports[new_rid].zero_compile, \
            fleet.warm_reports[new_rid]
        # the respawned world quotes bit-identically
        assert fleet.query(month, qx) == vals[0]
        assert sorted(ctl.probe().values()) == ["live", "live"]
    finally:
        ctl.close()
    rep = replay_journal(journal)
    assert rep.clean, rep
    assert ctl.sweep() == {"segments": [], "fds": []}


# -- exactly-once recovery of ANY declared shape -----------------------------


@pytest.mark.timeout(420)
@pytest.mark.parametrize("spec", [
    TopologySpec(replicas=2, replica_mode="thread"),
    pytest.param(TopologySpec(replicas=2, replica_mode="process",
                              transport="shm"), marks=_SHM),
    TopologySpec(replicas=1, replica_mode="process", transport="socket"),
], ids=["thread", "proc-shm", "proc-socket"])
def test_recover_rebuilds_the_declared_shape(tmp_path, spec):
    """Whole-controller crash with requests in flight: the journal's
    topology mark alone rebuilds the SAME declared shape — replica
    count, mode AND transport — replaying clean, serving bit-identical
    quotes, with zero fresh compiles from the registry warm pool."""
    from fm_returnprediction_tpu.serving import ServingFleet

    rng = np.random.default_rng(11)
    state, months = _tiny_state(rng)
    journal = tmp_path / "journal.jsonl"
    reg_dir = tmp_path / "registry"
    fleet = ServingFleet(state, spec.replicas,
                         replica_mode=spec.replica_mode,
                         transport=spec.transport, journal=str(journal),
                         registry_dir=str(reg_dir),
                         max_batch=16, max_latency_ms=2.0)
    ctl = TopologyController(spec, fleet=fleet)
    qx = rng.standard_normal(4).astype(np.float32)
    month = int(months[0])
    before = fleet.query(month, qx)
    # in-flight submits + abrupt death: no close-out, no rotation
    for _ in range(4):
        fleet.submit(month, qx)
    fleet.hard_crash()

    ctl2, report = TopologyController.recover(
        journal, state=state, registry_dir=str(reg_dir),
        max_batch=16, max_latency_ms=2.0)
    try:
        assert ctl2.spec == spec
        assert report.clean, report
        assert report.n_replicas == spec.replicas
        if spec.replica_mode == "process":
            assert report.zero_compile_starts == spec.replicas, report
        assert ctl2.fleet.query(month, qx) == before
        # the journal carried the FULL shape, not just a size
        assert report.journal.last_topology == spec.to_mark()
    finally:
        ctl2.close()
    assert ctl2.sweep() == {"segments": [], "fds": []}


@_SHM
@pytest.mark.timeout(420)
def test_recover_mixed_shape_rebuilds_the_grid_pool(tmp_path):
    """The MIXED shape: process replicas + a grid pool + its embedded
    broker, declared in one spec. Recovery rebuilds the fleet from the
    journal and the pool from the supplied panel; the inventory lists
    every member kind and the rebuilt pool contracts correctly."""
    from fm_returnprediction_tpu.serving import ServingFleet

    rng = np.random.default_rng(17)
    state, months = _tiny_state(rng)
    journal = tmp_path / "journal.jsonl"
    t, n, p = 24, 40, 3
    gx = rng.standard_normal((t, n, p))
    gy = (gx @ (0.1 * rng.standard_normal(p))
          + 0.2 * rng.standard_normal((t, n)))
    uni = np.ones((1, t, n), bool)
    spec = TopologySpec(replicas=1, replica_mode="process",
                        transport="shm", grid_procs=2)
    fleet = ServingFleet(state, 1, replica_mode="process",
                         transport="shm", journal=str(journal),
                         max_batch=16, max_latency_ms=2.0)
    ctl = TopologyController(spec, fleet=fleet)
    fleet.hard_crash()

    ctl2, report = TopologyController.recover(
        journal, state=state, panel=(gy, gx, uni),
        max_batch=16, max_latency_ms=2.0)
    try:
        assert ctl2.spec == spec and report.clean
        assert ctl2.pool is not None
        counts = {}
        for m in ctl2.members():
            counts[m.kind] = counts.get(m.kind, 0) + 1
        assert counts == {"router": 1, "replica_process": 1,
                          "grid_worker": 2, "broker": 1}
        uidx = np.zeros(1, np.int64)
        col_sel = np.ones((1, p), bool)
        window = np.ones((1, t), bool)
        stats = ctl2.pool.contract(uidx, col_sel, window)
        assert np.isfinite(stats.gram).all()
        assert stats.n.sum() == t * n
    finally:
        ctl2.close()
    assert ctl2.sweep() == {"segments": [], "fds": []}


# -- the grid: degraded N-1, refusal knob, chaos rank death, re-election -----


def _grid_fixture(rng, t=24, n=40, p=3):
    x = rng.standard_normal((t, n, p))
    y = x @ (0.1 * rng.standard_normal(p)) + 0.2 * rng.standard_normal((t, n))
    uni = np.ones((1, t, n), bool)
    uidx = np.zeros(1, np.int64)
    col_sel = np.ones((1, p), bool)
    window = np.ones((1, t), bool)
    return y, x, uni, uidx, col_sel, window


@pytest.mark.timeout(420)
def test_grid_worker_death_degrades_to_disclosed_partial_sum():
    """SIGKILL one of three workers between rounds: the next contract
    DISCLOSES a degraded N-1 world (survivors keep their ORIGINAL firm
    slices, the center ships so partial sums stay exact w.r.t. the full
    world) and repeats bit-identically."""
    from fm_returnprediction_tpu.specgrid import multiproc

    rng = np.random.default_rng(23)
    y, x, uni, uidx, col_sel, window = _grid_fixture(rng)
    pool = multiproc.SpecGridWorkerPool(3, y, x, uni)
    try:
        full = pool.contract(uidx, col_sel, window)
        assert pool.degraded_ranks == ()
        pool.workers[1].kill()  # shard 2's corpse, found mid-merge
        deg = pool.contract(uidx, col_sel, window)
        assert pool.degraded_ranks == (2,)
        # survivors cover strictly fewer firms, against the SAME center
        assert deg.n.sum() < full.n.sum()
        np.testing.assert_array_equal(deg.center, full.center)
        rerun = pool.contract(uidx, col_sel, window)
        np.testing.assert_array_equal(rerun.gram, deg.gram)
        np.testing.assert_array_equal(rerun.n, deg.n)
    finally:
        pool.close()


@pytest.mark.timeout(420)
def test_degraded_grid_refusal_knob(monkeypatch):
    """``FMRP_TOPO_DEGRADED_GRID=0`` is the exact-world-only contract:
    a worker death REFUSES with the dead shard disclosed, instead of
    silently serving a partial sum."""
    from fm_returnprediction_tpu.specgrid import multiproc

    monkeypatch.setenv("FMRP_TOPO_DEGRADED_GRID", "0")
    rng = np.random.default_rng(29)
    y, x, uni, uidx, col_sel, window = _grid_fixture(rng)
    pool = multiproc.SpecGridWorkerPool(2, y, x, uni)
    try:
        pool.contract(uidx, col_sel, window)
        pool.workers[0].kill()
        with pytest.raises(DegradedWorldError) as ei:
            pool.contract(uidx, col_sel, window)
        assert ei.value.dead_ranks == (1,)
    finally:
        pool.close()


@pytest.mark.timeout(420)
def test_chaos_rank_death_inside_child_and_broker_reelection():
    """The cross-process campaign on the grid: (a) a proc-targeted
    ``grid.rank_death`` SIGKILL fires INSIDE worker 2 on its first job
    — the pool degrades to the disclosed N-1 world mid-contract, no
    parent-side cooperation; (b) an injected broker death mid-round
    (``dist.broker_round``) is RE-ELECTED — world respawned, round
    fanned out again — and the answer matches the pre-fault full world
    bit-identically."""
    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.specgrid import multiproc

    rng = np.random.default_rng(31)
    y, x, uni, uidx, col_sel, window = _grid_fixture(rng)

    # (a) the bomb rides env into worker 2 only; armed ONLY while the
    # pool spawns so degraded respawns come up clean
    with FaultPlan({"grid.rank_death":
                    FaultSpec(times=1, sigkill=True, proc="2")}):
        pool = multiproc.SpecGridWorkerPool(3, y, x, uni)
    try:
        deg = pool.contract(uidx, col_sel, window)
        assert pool.degraded_ranks == (2,)
        assert deg.n.sum() < y.size
    finally:
        pool.close()

    # (b) broker death mid-round: parent-side plan only (never enters
    # any child env — the pool is created OUTSIDE the plan)
    reelect = telemetry.registry().counter(
        "fmrp_topology_broker_reelections_total")
    before_ct = reelect.value
    pool = multiproc.SpecGridWorkerPool(2, y, x, uni)
    try:
        full = pool.contract(uidx, col_sel, window)
        with FaultPlan({"dist.broker_round": FaultSpec(times=1)}) as p:
            again = pool.contract(uidx, col_sel, window)
            assert p.fired["dist.broker_round"] == 1
        assert pool.degraded_ranks == ()  # re-election, NOT degrade
        assert reelect.value == before_ct + 1
        np.testing.assert_array_equal(again.gram, full.gram)
        np.testing.assert_array_equal(again.n, full.n)
    finally:
        pool.close()


# -- the autoscaler routes through the controller ----------------------------


def test_autoscale_routes_through_the_topology_controller(tmp_path):
    """PR-12 elasticity becomes a topology verb: with a controller
    attached, the supervisor's scale-out updates the DECLARED spec and
    journals a fresh topology mark — the record recovery rebuilds from
    — instead of drifting the live world away from the declaration."""
    from fm_returnprediction_tpu.serving import (
        AdmissionPolicy,
        AutoscalePolicy,
        ServingFleet,
    )

    rng = np.random.default_rng(37)
    state, months = _tiny_state(rng)
    journal = tmp_path / "journal.jsonl"
    clk = [1000.0]
    fleet = ServingFleet(
        state, 1, max_batch=8, max_queue=8, auto_flush=False,
        journal=str(journal),
        admission=AdmissionPolicy(max_occupancy=1.01),
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=2,
                                  cooldown_s=10.0, out_occupancy=0.5,
                                  in_occupancy=0.2, in_ticks=2),
        admission_clock=lambda: clk[0],
    )
    spec = TopologySpec(replicas=1, replica_mode="thread")
    ctl = TopologyController(spec, fleet=fleet)
    try:
        qx = rng.standard_normal(4).astype(np.float32)
        futs = [fleet.submit(int(months[0]), qx) for _ in range(6)]
        actions = fleet.supervisor.tick()
        assert any(a.startswith("scale-out:+1") for a in actions), actions
        # the DECLARATION moved with the world
        assert ctl.spec.replicas == 2
        marks = [json.loads(ln) for ln in
                 journal.read_text().splitlines() if ln.strip()]
        topo = [json.loads(m["topo"]) for m in marks
                if m.get("ev") == "mark" and m.get("label") == "topology"]
        assert topo[-1]["replicas"] == 2
        fleet.flush_all()
        for f in futs:
            f.result(timeout=10)
    finally:
        ctl.close()


def test_member_rows_are_plain_data():
    m = Member(kind="router", ident="router", pid=1, status="live")
    assert (m.kind, m.status) == ("router", "live")
