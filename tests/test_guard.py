"""Guardrail layer: contracts, drift sentinels, jit-safe numerical guards.

Three claims under test (ISSUE acceptance):

1. CONTRACTS — each invariant rule catches its corruption class at the
   declared severity, and the severity ladder maps to the right typed
   error / warning / quarantine behavior.
2. DRIFT — a same-fingerprint rerun whose artifact moments moved beyond
   band fails loudly with a per-column report; an identical rerun
   short-circuits on the content sha; a different fingerprint
   re-baselines instead of crying wolf.
3. SENTINELS ARE SEMANTICALLY FREE — on clean data, outputs are
   bit-identical with guards on vs off, the guard-off jaxpr contains no
   guard code at all (proved by making the sentinel helpers explode and
   tracing anyway), and arming guards costs zero extra traces per
   configuration on the OLS/Gram hot paths.
"""

import functools
import warnings

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.guard import checks, contracts, drift
from fm_returnprediction_tpu.resilience.errors import (
    ContractViolationError,
    DriftDetectedError,
    IngestRejectedError,
)

pytestmark = pytest.mark.guard


@pytest.fixture(autouse=True)
def _clean_counters():
    checks.reset()
    yield
    checks.reset()


def _tiny_panel(t=10, n=8, seed=3, dtype=np.float64):
    from fm_returnprediction_tpu.panel.dense import DensePanel

    rng = np.random.default_rng(seed)
    values = rng.standard_normal((t, n, 3)).astype(dtype) * 0.1
    mask = np.ones((t, n), dtype=bool)
    values[~mask] = np.nan
    months = (
        np.datetime64("2000-01-31", "ns")
        + np.arange(t) * np.timedelta64(31, "D").astype("timedelta64[ns]")
    )
    return DensePanel(
        values=values,
        mask=mask,
        months=months.astype("datetime64[ns]"),
        ids=np.arange(100, 100 + n),
        var_names=["retx", "size", "bm"],
    )


# -- contracts: severity ladder --------------------------------------------


def test_clean_panel_passes_all_contracts():
    panel = _tiny_panel()
    audit = contracts.AuditRecord()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        probe = contracts.check_panel(panel, dtype=np.float64, audit=audit)
    assert audit.ok()
    assert probe["columns"]["retx"]["finite"] > 0  # probe doubles as stats


def test_fail_severity_raises_typed_error():
    import dataclasses

    panel = _tiny_panel()
    ids = np.asarray(panel.ids).copy()
    ids[1] = ids[0]  # duplicated permno
    bad = dataclasses.replace(panel, ids=ids)
    audit = contracts.AuditRecord()
    with pytest.raises(ContractViolationError, match="panel.key_unique"):
        contracts.check_panel(bad, audit=audit)
    assert "panel.key_unique" in audit.names()  # named in the audit record


def test_warn_severity_warns_and_records_but_passes():
    import dataclasses

    panel = _tiny_panel()
    perm = np.random.default_rng(0).permutation(len(panel.ids))
    bad = dataclasses.replace(
        panel,
        ids=np.asarray(panel.ids)[perm],
        values=np.asarray(panel.values)[:, perm, :],
        mask=np.asarray(panel.mask)[:, perm],
    )
    audit = contracts.AuditRecord()
    with pytest.warns(contracts.GuardWarning, match="panel.ids_sorted"):
        contracts.check_panel(bad, audit=audit)
    assert audit.names() == ["panel.ids_sorted"]


def test_calendar_and_bounds_rules():
    import dataclasses

    panel = _tiny_panel()
    months = np.asarray(panel.months).copy()
    months[-1] = months[-2]  # stale repeated month stamp
    with pytest.raises(ContractViolationError, match="panel.calendar_monotone"):
        contracts.check_panel(dataclasses.replace(panel, months=months))

    vals = np.asarray(panel.values).copy()
    vals[0, 0, 1] = 1e20  # f32-overflow scale spike
    with pytest.raises(ContractViolationError, match="panel.value_bounds"):
        contracts.check_panel(dataclasses.replace(panel, values=vals))

    vals = np.asarray(panel.values).copy()
    vals[0, 0, 0] = -1.5  # impossible simple return
    with pytest.raises(ContractViolationError, match="panel.return_bounds_low"):
        contracts.check_panel(dataclasses.replace(panel, values=vals))


def test_infinite_entries_fail_value_bounds():
    """A literal ±inf is an ALREADY-overflowed value — the finite-moment
    scan never sees it, so the rule must count infs explicitly."""
    import dataclasses

    panel = _tiny_panel()
    vals = np.asarray(panel.values).copy()
    vals[0, 0, 1] = np.inf
    with pytest.raises(ContractViolationError, match="panel.value_bounds"):
        contracts.check_panel(dataclasses.replace(panel, values=vals))


def test_unreadable_panel_raises_typed_error():
    """A panel the probe cannot even reduce (wrong rank — a torn
    checkpoint) must surface as the typed ContractViolationError the
    taskgraph ledger expects, not a raw unpacking error."""
    import dataclasses

    panel = _tiny_panel()
    bad = dataclasses.replace(
        panel, values=np.asarray(panel.values)[:, :, 0]
    )
    audit = contracts.AuditRecord()
    with pytest.raises(ContractViolationError, match="unreadable"):
        contracts.check_panel(bad, audit=audit)
    assert audit.names() == ["panel.schema"]


def test_host_boundary_counters_for_fused_sweeps():
    """Fused sweep programs inline monthly_cs_ols/fama_macbeth (records
    tracer-skipped); the host-boundary recorders carry the audit from the
    pulled numpy leaves — including subset-stacked ones."""
    from fm_returnprediction_tpu.ops.ols import CSRegressionResult

    t, p, s = 6, 2, 3
    cs = CSRegressionResult(
        slopes=np.zeros((s, t, p)),
        intercept=np.zeros((s, t)),
        r2=np.zeros((s, t)),
        n_obs=np.full((s, t), 10.0),
        month_valid=np.ones((s, t), bool),
    )
    bad = np.asarray(cs.slopes)
    bad[1, 2, 0] = np.nan  # one poisoned month in one subset
    with checks.guards(True):
        checks.record_cs_host("sweep.test", cs)
    assert checks.counters() == {"sweep.test.nonfinite_solve_months": 1}


def test_evaluation_short_circuits_on_blocking_violation():
    """A mis-shaped subject must not crash later rules: evaluation stops at
    the first blocking violation."""
    rules = [
        contracts.Rule("a.first", "fail", lambda s: "broken"),
        contracts.Rule("a.second", "fail", lambda s: 1 / 0 and None),
    ]
    found = contracts.evaluate(rules, object())
    assert [v.rule for v in found] == ["a.first"]


def test_crashed_check_is_reported_not_raised():
    rules = [contracts.Rule("b.crashy", "warn", lambda s: [][1] and None)]
    found = contracts.evaluate(rules, object())
    assert found and "crashed" in found[0].detail


def test_screen_artifact_quarantines_and_continues():
    audit = contracts.AuditRecord()
    empty = pd.DataFrame()
    rules = contracts.frame_rules("opt", blocking="quarantine")
    with pytest.warns(contracts.GuardWarning, match="quarantined"):
        out = contracts.screen_artifact("opt", empty, rules, audit)
    assert out is None
    assert audit.quarantined == ["opt"]
    # a healthy artifact passes through untouched
    ok = pd.DataFrame({"x": [1.0]})
    assert contracts.screen_artifact("opt", ok, rules, audit) is ok


def test_frame_rules_on_formatted_table():
    """The formatted (string-valued) Table 2 coerces: blanks are NaN, a
    numeric-looking table passes, an all-blank one fails."""
    good = pd.DataFrame({"a": ["0.123", ""], "b": ["-1.5", "2,000"]})
    assert contracts.evaluate(contracts.frame_rules("t2"), good) == []
    flood = pd.DataFrame({"a": ["", ""], "b": ["", ""]})
    found = contracts.evaluate(contracts.frame_rules("t2"), flood)
    assert found and found[0].rule == "t2.nonfinite_flood"


# -- contracts: shared cross-section definition ----------------------------


def _tiny_state(t=12, n=20, p=3, seed=5):
    from fm_returnprediction_tpu.serving import build_serving_state

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = (0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    return build_serving_state(y, x, mask, window=t // 2,
                               min_periods=t // 4), x, mask


def test_validate_cross_section_uses_shared_rules():
    from fm_returnprediction_tpu.serving.ingest import validate_cross_section

    state, x, _ = _tiny_state()
    n, p = x.shape[1], x.shape[2]
    # NaN flood → the shared cs.nan_flood rule, message text preserved
    with pytest.raises(IngestRejectedError, match="all-NaN"):
        validate_cross_section(
            state, np.full(n, np.nan), np.full((n, p), np.nan),
            np.ones(n, bool),
        )
    # value bound → the shared cs.value_bounds rule
    spiked = x[-1].copy()
    spiked[:, 0] *= np.float32(1e20)
    with pytest.raises(IngestRejectedError, match="cs.value_bounds"):
        validate_cross_section(
            state, np.full(n, np.nan), spiked, np.ones(n, bool)
        )
    # a clean month passes and coerces dtype
    y, xv, m = validate_cross_section(
        state, np.full(n, np.nan), x[-1], np.ones(n, bool)
    )
    assert xv.dtype == state.dtype


def test_stale_repeat_detected_only_for_new_label():
    from fm_returnprediction_tpu.serving.ingest import validate_cross_section

    state, x, mask = _tiny_state()
    n = x.shape[1]
    last_x, last_mask = x[-1], mask[-1]
    new_month = np.datetime64("2099-01-31", "ns")
    # the SAME cross-section under a NEW label: stale feed
    with pytest.raises(IngestRejectedError, match="cs.stale_repeat"):
        validate_cross_section(
            state, np.full(n, np.nan), last_x, last_mask, month=new_month
        )
    # same label (merge) is legal
    validate_cross_section(
        state, np.full(n, np.nan), last_x, last_mask, month=state.months[-1]
    )
    # a genuinely different cross-section under the new label is legal
    other = last_x + np.float32(0.25)
    validate_cross_section(
        state, np.full(n, np.nan), other, last_mask, month=new_month
    )


# -- drift sentinel --------------------------------------------------------


def test_drift_identical_rerun_short_circuits(tmp_path):
    df = pd.DataFrame({"coef": [0.1, 0.2], "tstat": [2.0, 3.0]})
    s1 = drift.DriftSentinel(tmp_path, "fp")
    s1.check("table_2", drift.summarize_frame(df))
    s1.raise_on_drift()
    s1.commit()
    s2 = drift.DriftSentinel(tmp_path, "fp")
    assert s2.check("table_2", drift.summarize_frame(df.copy())) == []


def test_drift_beyond_band_fails_with_per_column_report(tmp_path):
    df = pd.DataFrame({"coef": [0.1, 0.2], "tstat": [2.0, 3.0]})
    s1 = drift.DriftSentinel(tmp_path, "fp")
    s1.check("table_2", drift.summarize_frame(df))
    s1.commit()

    moved = df.copy()
    moved["tstat"] = [2.0, 15.0]  # the silent-regression scenario
    s2 = drift.DriftSentinel(tmp_path, "fp")
    found = s2.check("table_2", drift.summarize_frame(moved))
    assert found and all(v.rule == "drift.table_2" for v in found)
    assert any("tstat" in v.detail for v in found)  # per-column report
    with pytest.raises(DriftDetectedError, match="tstat"):
        s2.raise_on_drift()
    # the trusted manifest was NOT overwritten by the failing run
    s3 = drift.DriftSentinel(tmp_path, "fp")
    assert s3.check("table_2", drift.summarize_frame(df)) == []


def test_drift_within_band_passes_and_rebaselines(tmp_path):
    df = pd.DataFrame({"coef": [0.1, 0.2]})
    s1 = drift.DriftSentinel(tmp_path, "fp")
    s1.check("table_2", drift.summarize_frame(df))
    s1.commit()
    nudged = df + 1e-9  # far inside the default band
    s2 = drift.DriftSentinel(tmp_path, "fp")
    assert s2.check("table_2", drift.summarize_frame(nudged)) == []
    # different fingerprint: comparison meaningless → re-baseline, no drift
    s3 = drift.DriftSentinel(tmp_path, "other-data")
    assert s3.rebaselined
    moved = df * 100
    assert s3.check("table_2", drift.summarize_frame(moved)) == []


def test_drift_band_env_overrides_are_live(monkeypatch):
    """FMRP_DRIFT_* must resolve at instantiation, not module import."""
    monkeypatch.setenv("FMRP_DRIFT_RTOL", "0.25")
    monkeypatch.setenv("FMRP_DRIFT_ATOL", "0.5")
    band = drift.DriftBand()
    assert band.rtol == 0.25 and band.atol == 0.5
    monkeypatch.delenv("FMRP_DRIFT_RTOL")
    monkeypatch.delenv("FMRP_DRIFT_ATOL")
    assert drift.DriftBand().rtol == 1e-3


def test_drift_band_overrides():
    a = {"kind": "frame", "sha256": "x", "shape": [1, 1],
         "columns": {"c": {"finite": 1, "size": 1, "mean": 1.0, "std": 0.0,
                           "min": 1.0, "max": 1.0}}}
    b = {"kind": "frame", "sha256": "y", "shape": [1, 1],
         "columns": {"c": {"finite": 1, "size": 1, "mean": 1.05, "std": 0.0,
                           "min": 1.05, "max": 1.05}}}
    assert drift.compare_summaries("t", a, b)  # default band: drift
    wide = drift.DriftBand(rtol=0.1, atol=0.0)
    assert drift.compare_summaries("t", a, b, band=wide) == []


def test_pipeline_drift_end_to_end(tmp_path):
    """run_pipeline(audit_dir=...): first run baselines, identical rerun
    passes, a tampered manifest (simulating moved numbers) fails loudly."""
    import json

    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.pipeline import run_pipeline

    kw = dict(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=20, n_months=36),
        make_figure=False, make_deciles=False, make_serving=False,
        compile_pdf=False, audit_dir=tmp_path,
    )
    run_pipeline(**kw)
    manifest = tmp_path / drift.MANIFEST_NAME
    assert manifest.exists()
    run_pipeline(**kw)  # identical rerun: clean

    # tamper the baseline as if the previous run's slopes were different
    meta = json.loads(manifest.read_text())
    col = next(iter(meta["artifacts"]["table_2"]["columns"].values()))
    col["mean"] = (col["mean"] or 0.0) + 1.0
    meta["artifacts"]["table_2"]["sha256"] = "not-the-same"
    manifest.write_text(json.dumps(meta))
    with pytest.raises(DriftDetectedError, match="table_2"):
        run_pipeline(**kw)


# -- sentinels: violations are counted -------------------------------------


def test_overflow_sentinel_counts_nonfinite_gram():
    from fm_returnprediction_tpu.ops.ols import monthly_cs_ols

    rng = np.random.default_rng(0)
    t, n, p = 6, 16, 3
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    x[..., 0] *= np.float32(1e25)  # x² overflows f32
    y = np.zeros((t, n), np.float32)
    mask = np.ones((t, n), bool)
    with checks.guards(True):
        monthly_cs_ols(y, x, mask, solver="normal")
    got = checks.counters()
    assert got.get("ols.monthly_cs_ols.gram_nonfinite_entries", 0) > 0


def test_ingest_overflow_quarantined_with_named_violation():
    """Two fences against an f32 scale spike: the service path trips the
    value-bound contract BEFORE contraction; a direct library ingest that
    skips validation is still stopped by the post-contraction stats
    sentinel (x = 1e19 is a finite f32 whose square is inf)."""
    from fm_returnprediction_tpu.serving import ERService
    from fm_returnprediction_tpu.serving.ingest import ingest_month

    state, x, _ = _tiny_state()
    n, p = x.shape[1], x.shape[2]
    spiked = np.full((n, p), np.float32(1e20))
    with checks.guards(True):
        with ERService(state, warm=False, auto_flush=False) as svc:
            ok = svc.ingest_month(
                np.full(n, np.nan), spiked, np.ones(n, bool),
                np.datetime64("2099-03-31", "ns"),
            )
            assert not ok and svc.degraded
            (reason,) = svc.quarantined_months().values()
            assert "cs.value_bounds" in reason
            assert "cs.value_bounds" in svc.audit.names()

        # second fence: bypass validation, overflow the contraction
        # (finite y so the rows are complete-case valid and contract)
        with pytest.raises(IngestRejectedError, match="cs.nonfinite_stats"):
            ingest_month(
                state, np.zeros(n, np.float32),
                np.full((n, p), np.float32(1e19)), np.ones(n, bool),
                np.datetime64("2099-03-31", "ns"),
            )
    assert checks.counters().get(
        "serving.ingest.gram_nonfinite_entries", 0
    ) > 0


def test_audit_record_report_roundtrip():
    audit = contracts.AuditRecord()
    audit.record([contracts.Violation("x.y", "warn", "d")])
    audit.record_counters({"a.b": 2, "zero": 0})
    audit.quarantined.append("specgrid_scenarios")
    d = audit.as_dict()
    assert d["violations"][0]["rule"] == "x.y"
    assert d["counters"] == {"a.b": 2}
    assert not audit.ok()
    assert "x.y" in audit.report() and "specgrid_scenarios" in audit.report()
