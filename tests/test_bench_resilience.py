"""Bench artifact resilience (round 4).

r04 run 1 lost the real-shape number to a mid-run backend fault: the
remote-compile helper 500'd during the real section, and two later
sections found the tunnel dead. These tests pin the rescue machinery that
turns that scenario into a disclosed partial artifact instead of a lost
round:

- the global watchdog emits the artifact-so-far when a section hangs;
- a backend fault in the real section triggers a CPU-pinned subprocess
  rescue whose result is keyed cold/warm by what the child actually did
  and labelled ``cpu-fallback`` all the way into the headline metric name;
- ``_emit_line`` prints exactly ONE JSON line no matter who calls it.
"""

import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

_REPO = Path(__file__).parent.parent


def _fresh_bench():
    spec = importlib.util.spec_from_file_location("bench", _REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _clean_env(**overrides):
    # reuse the production child-env builder (CPU pin + sitecustomize
    # stripping) so the tests and the rescue path cannot silently diverge
    env = _fresh_bench()._child_env(str(_REPO))
    env.update(overrides)
    return env


def _run_child(code: str, timeout: float = 300, **env_overrides):
    # the budget bounds a CPU-quota-dependent wall (the fast-shape
    # sections alone are ~90-150 s depending on host throttling); it is
    # a hang guard, not a latency pin — the deadline mechanics under
    # test have their own in-child FMRP_BENCH_DEADLINE_S clock
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout, env=_clean_env(**env_overrides), cwd=str(_REPO),
    )
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    return proc, lines


def test_watchdog_emits_partial_artifact():
    """A hanging section costs only the remaining sections: the watchdog
    prints the sections measured so far and hard-exits."""
    proc, lines = _run_child(
        """
import time
import bench
bench._bench_pipeline = lambda fast: {"pipeline_warm_s": 1.5,
                                      "pipeline_shape": "T9_N9"}
bench._bench_pipeline_real = lambda fast: time.sleep(300)
bench.main()
print("UNREACHABLE")
""",
        FMRP_BENCH_DEADLINE_S="3",
    )
    assert "UNREACHABLE" not in proc.stdout
    assert len(lines) == 1, proc.stdout + proc.stderr
    got = json.loads(lines[0])
    assert got["metric"] == "e2e_pipeline_T9_N9_warm_wall_s"
    assert got["value"] == 1.5
    assert got["extra"]["bench_deadline_exceeded_s"] == 3.0


def test_rescued_number_renames_headline_metric():
    """A cpu-fallback real number must be disclosed in the metric name
    itself, not only in a buried extra key."""
    proc, lines = _run_child(
        """
import bench
bench._bench_pipeline = lambda fast: {"pipeline_warm_s": 1.0,
                                      "pipeline_shape": "T1_N1"}
bench._bench_pipeline_real = lambda fast: {
    "real_pipeline_warm_s": 42.0, "real_pipeline_shape": "T600_N22000",
    "real_pipeline_device": "cpu-fallback",
}
bench._bench_kernel = lambda fast: {}
bench._bench_daily_fullscale = lambda fast: {}
bench._bench_pallas = lambda fast: {}
bench._bench_mesh8 = lambda fast: {}
bench.main()
""",
        # keep the un-stubbed sections (serving, specgrid, estimators,
        # resilience) at their fast shapes: this test pins emit-line
        # mechanics, not their numbers, and the small/fuseprobe CPU
        # ladders are fast-gated off. The backtest consumer leg stands up
        # a second fleet — skipped via its own knob to bound child wall.
        FMRP_BENCH_FAST="1",
        FMRP_BENCH_BACKTEST="0",
    )
    assert len(lines) == 1, proc.stdout + proc.stderr
    got = json.loads(lines[0])
    assert got["metric"] == "e2e_pipeline_T600_N22000_warm_cpu_fallback_wall_s"
    assert got["value"] == 42.0


def test_backend_fault_triggers_cpu_rescue(monkeypatch):
    """A backend fault in the real section produces a disclosed CPU number
    from a REAL child pipeline run, keyed cold (no checkpoint existed), with
    the accel error attributed to in-repo frames."""
    monkeypatch.setenv("FMRP_BENCH_REAL_MONTHS", "36")
    monkeypatch.setenv("FMRP_BENCH_REAL_FIRMS", "120")
    monkeypatch.setenv("FMRP_BENCH_REAL_BUDGET_S", "300")
    bench = _fresh_bench()

    def boom(raw_dir):
        raise RuntimeError("INTERNAL: remote_compile: HTTP 500 (simulated)")

    monkeypatch.setattr(bench, "_run_pipeline_timed", boom)
    out = bench._bench_pipeline_real(False)
    assert out["real_pipeline_device"] == "cpu-fallback"
    # the parent died before ingest → the child paid the cold path and the
    # result must not masquerade as the warm repeat-run number
    assert "real_pipeline_cold_s" in out and "real_pipeline_warm_s" not in out
    assert out["real_pipeline_cold_s"] > 0
    assert "build_panel" in out["real_pipeline_cold_stage_s"]
    assert "HTTP 500" in out["real_pipeline_accel_error"]
    assert out["real_pipeline_accel_error_frames"]


def test_emit_line_prints_exactly_once(capsys):
    bench = _fresh_bench()
    extra = {"pipeline_warm_s": 2.0, "pipeline_shape": "T2_N2"}
    bench._emit_line(extra)
    bench._emit_line(extra)
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert len(lines) == 1
    assert json.loads(lines[0])["value"] == 2.0
