"""Property-based differential: time-sharded rolling kernels vs the
single-device ``ops.rolling`` kernels over random shapes, windows,
min_periods, NaN densities, and mesh sizes (2/4/8 of the virtual devices).

The fixed cases in ``test_time_sharded.py`` pin the pipeline's windows;
this sweep covers the space between them — in particular every relation of
window to shard length up to the single-hop limit, and sequences whose
length is not a multiple of the mesh (the NaN-pad + trim path).
"""

import numpy as np
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.rolling import (
    rolling_mean,
    rolling_std,
    rolling_sum,
)
from fm_returnprediction_tpu.parallel import make_mesh
from fm_returnprediction_tpu.parallel.time_sharded import (
    rolling_mean_time_sharded,
    rolling_std_time_sharded,
    rolling_sum_time_sharded,
)

_MESHES = {}


def _mesh(p):
    if p not in _MESHES:
        import jax

        _MESHES[p] = make_mesh(n_devices=p, axis_name="time",
                               devices=jax.devices()[:p])
    return _MESHES[p]


@st.composite
def _cases(draw):
    p = draw(st.sampled_from([2, 4, 8]))
    shard_len = draw(st.integers(min_value=2, max_value=12))
    ragged = draw(st.integers(min_value=0, max_value=p - 1))
    t = p * shard_len - ragged  # padded length p*shard_len
    window = draw(st.integers(min_value=1, max_value=shard_len))
    min_periods = draw(st.integers(min_value=1, max_value=window))
    nan_frac = draw(st.floats(min_value=0.0, max_value=0.6))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, 3))
    x[rng.random((t, 3)) < nan_frac] = np.nan
    return p, x, window, min_periods


@given(_cases())
@settings(max_examples=25, deadline=None)
def test_time_sharded_matches_single_device(case):
    p, x, window, min_periods = case
    mesh = _mesh(p)
    pairs = [
        (rolling_sum, rolling_sum_time_sharded),
        (rolling_mean, rolling_mean_time_sharded),
        (rolling_std, rolling_std_time_sharded),
    ]
    for single, sharded in pairs:
        want = np.asarray(single(jnp.asarray(x), window, min_periods))
        got = np.asarray(sharded(x, window, min_periods, mesh=mesh))
        np.testing.assert_allclose(
            got, want, rtol=1e-9, atol=1e-12, equal_nan=True,
            err_msg=f"{single.__name__} p={p} w={window} mp={min_periods}",
        )
