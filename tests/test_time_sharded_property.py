"""Property-based differential: time-sharded rolling kernels vs the
single-device ``ops.rolling`` kernels over random shapes, windows,
min_periods, NaN densities, and mesh sizes (2/4/8 of the virtual devices).

The fixed cases in ``test_time_sharded.py`` pin the pipeline's windows;
this sweep covers the space between them — in particular every relation of
window to shard length up to the single-hop limit, and sequences whose
length is not a multiple of the mesh (the NaN-pad + trim path).
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # tier-1 must COLLECT cleanly without the optional dep
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.rolling import (
    rolling_mean,
    rolling_std,
    rolling_sum,
)
from fm_returnprediction_tpu.parallel import make_mesh
from fm_returnprediction_tpu.parallel.time_sharded import (
    rolling_mean_time_sharded,
    rolling_std_time_sharded,
    rolling_sum_time_sharded,
)

_MESHES = {}


def _mesh(p):
    if p not in _MESHES:
        import jax

        _MESHES[p] = make_mesh(n_devices=p, axis_name="time",
                               devices=jax.devices()[:p])
    return _MESHES[p]


@st.composite
def _cases(draw):
    p = draw(st.sampled_from([2, 4, 8]))
    shard_len = draw(st.integers(min_value=2, max_value=12))
    ragged = draw(st.integers(min_value=0, max_value=p - 1))
    t = p * shard_len - ragged  # padded length p*shard_len
    window = draw(st.integers(min_value=1, max_value=shard_len))
    min_periods = draw(st.integers(min_value=1, max_value=window))
    nan_frac = draw(st.floats(min_value=0.0, max_value=0.6))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, 3))
    x[rng.random((t, 3)) < nan_frac] = np.nan
    return p, x, window, min_periods


# Tolerance contract (reassociation-aware — see the failure analysis below).
#
# The sharded route computes the SAME cumulative sums as ``ops.rolling`` but
# in a different association order: shard-local cumsum + all-gathered
# exclusive prefix offset, instead of one sequential scan. Float addition is
# not associative, so bit-equality with the single-device route is not a
# theorem; each route's windowed sum carries a forward error of order
# ``T·eps·max|prefix|`` (~1e-12 abs here: T ≤ 96, |prefix| ≲ 50, f64
# eps 2.2e-16), and the DIFFERENCE between the two routes is bounded by the
# sum of both errors.  ``atol=1e-9`` for the sum/mean leaves ~500x headroom
# over that bound while still catching any semantic bug (wrong halo row,
# off-by-one offset) whose error is O(|x|) ~ 1, not O(eps).
#
# std needs its own contract: variance is ``Σx² − (Σx)²/n`` — linear in the
# moment errors, so the variance-domain comparison stays tight — but
# ``sqrt`` amplifies a δ-sized variance error to ``√δ`` when the true
# variance is ~0 (two near-equal values in a w=2 window: the draw that broke
# the old flat ``rtol=1e-9, atol=1e-12`` assertion at 3e-8 rel).  So std is
# asserted tight in the variance domain (got², want²) and with a √-aware
# absolute bound (√(2e-9) ≈ 4.5e-5, rounded up) in the std domain.
#
# Calibration check (round 5): a 500-case fresh-seed sweep of this exact
# case space observed worst diffs of 1.5e-14 (variance domain) and
# 2.6e-12 (std domain) — the asserted bounds carry ≥4 orders of margin
# over observed reassociation error while sitting ≥4 orders below any
# O(|x|) semantic-bug error.
_SUM_TOL = dict(rtol=1e-9, atol=1e-9)
_VAR_TOL = dict(rtol=1e-9, atol=1e-9)
_STD_TOL = dict(rtol=1e-7, atol=5e-5)


def _assert_std_close(got, want, err_msg):
    np.testing.assert_allclose(
        got * got, want * want, equal_nan=True,
        err_msg=err_msg + " [variance domain]", **_VAR_TOL,
    )
    np.testing.assert_allclose(
        got, want, equal_nan=True, err_msg=err_msg + " [std domain]",
        **_STD_TOL,
    )


@given(_cases())
@settings(max_examples=25, deadline=None)
def test_time_sharded_matches_single_device(case):
    p, x, window, min_periods = case
    mesh = _mesh(p)
    pairs = [
        (rolling_sum, rolling_sum_time_sharded),
        (rolling_mean, rolling_mean_time_sharded),
    ]
    for single, sharded in pairs:
        want = np.asarray(single(jnp.asarray(x), window, min_periods))
        got = np.asarray(sharded(x, window, min_periods, mesh=mesh))
        np.testing.assert_allclose(
            got, want, equal_nan=True,
            err_msg=f"{single.__name__} p={p} w={window} mp={min_periods}",
            **_SUM_TOL,
        )
    want = np.asarray(rolling_std(jnp.asarray(x), window, min_periods))
    got = np.asarray(rolling_std_time_sharded(x, window, min_periods, mesh=mesh))
    _assert_std_close(got, want, f"rolling_std p={p} w={window} mp={min_periods}")
