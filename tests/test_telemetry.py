"""Telemetry layer: spans, metrics registry, exporters — and the property
that observability is semantically FREE.

The acceptance contract mirrors the guard layer's (``test_guard_property``):

- OFF IS A NO-OP: with telemetry off, ``span`` returns one shared no-op
  context manager, and — off OR on — the traced jaxprs of the hot paths
  are byte-identical, because spans are host-side only and never enter a
  jitted program.
- ON IS INVISIBLE IN THE NUMBERS: Fama-MacBeth and the serving ``stats()``
  dicts are bit-identical with telemetry armed vs disarmed.
- SPANS NEST AND PROPAGATE: parent/trace IDs thread through nesting and
  across explicit thread hand-offs (``capture``/``attach``) — the task
  graph's watchdogged workers and the serving dispatch watchdog rely on
  exactly that.
- EXPORTS ARE WELL-FORMED AND DETERMINISTIC: the JSONL log round-trips
  and two exports of the same collector state are byte-identical; the
  Chrome trace is valid trace-event JSON.
- THE TRACE AND THE LEDGERS AGREE: the task graph's sqlite
  ``failure_log`` and the exported ``task.failure`` events describe the
  same failures (differential), and retry/checkpoint events match their
  plans.
"""

import json
import threading

import numpy as np
import pytest

from fm_returnprediction_tpu import telemetry

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.set_trace_dir(None)
    yield
    telemetry.reset()
    telemetry.set_trace_dir(None)


def _data(t=10, n=24, p=3, seed=7, dtype=np.float64):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(dtype)
    beta = (rng.standard_normal(p) * 0.05).astype(dtype)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(dtype)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(dtype)
    return y, x, mask


# -- span mechanics ---------------------------------------------------------


def test_off_mode_span_is_shared_noop():
    assert not telemetry.active()
    cm1, cm2 = telemetry.span("a"), telemetry.span("b", x=1)
    assert cm1 is cm2  # no allocation on the off path
    with cm1 as s:
        assert s is None
    assert telemetry.finished_spans() == []
    telemetry.event("ignored", k=1)  # off: dropped
    assert telemetry.standalone_events() == []


def test_span_nesting_and_ids():
    with telemetry.enabled(True):
        with telemetry.span("root", cat="stage") as root:
            telemetry.event("marker", k=1)
            with telemetry.span("child") as child:
                with telemetry.span("grandchild") as grand:
                    pass
        with telemetry.span("second_root") as r2:
            pass
    assert child.parent_id == root.span_id
    assert grand.parent_id == child.span_id
    assert child.trace_id == root.trace_id == root.span_id
    assert r2.parent_id is None and r2.trace_id != root.trace_id
    # the event landed on the open span, not the standalone list
    assert [e[0] for e in root.events] == ["marker"]
    assert telemetry.standalone_events() == []
    # completion order: children close before parents
    names = [s.name for s in telemetry.finished_spans()]
    assert names == ["grandchild", "child", "root", "second_root"]
    for s in telemetry.finished_spans():
        assert s.t1_ns >= s.t0_ns


def test_span_propagates_across_threads_via_attach():
    got = {}
    with telemetry.enabled(True):
        with telemetry.span("parent") as parent:
            handoff = telemetry.capture()

            def worker():
                # a fresh thread has NO ambient span …
                got["ambient"] = telemetry.current_span()
                # … until the captured parent is attached explicitly
                with telemetry.attach(handoff):
                    with telemetry.span("worker-span"):
                        pass

            th = threading.Thread(target=worker)
            th.start()
            th.join()
    assert got["ambient"] is None
    ws = [s for s in telemetry.finished_spans() if s.name == "worker-span"]
    assert len(ws) == 1
    assert ws[0].parent_id == parent.span_id
    assert ws[0].trace_id == parent.trace_id
    assert ws[0].thread_id != parent.thread_id


def test_span_records_exception_and_still_raises():
    with telemetry.enabled(True):
        with pytest.raises(ValueError):
            with telemetry.span("boom"):
                raise ValueError("pow")
    (s,) = telemetry.finished_spans()
    assert "pow" in s.attrs["error"]


# -- off-mode purity: jaxprs and numbers ------------------------------------


def test_jaxpr_identical_telemetry_on_vs_off():
    """Telemetry is host-side only: the traced program is byte-identical
    with spans armed or not (the analog of the guard layer's off-is-
    pristine property — but stronger: ON changes nothing either)."""
    import jax

    from fm_returnprediction_tpu.ops import ols

    y, x, mask = _data()
    with telemetry.enabled(False):
        jx_off = str(jax.make_jaxpr(
            lambda *a: ols._monthly_cs_ols(*a, solver="qr", guard=False)
        )(y, x, mask))
    with telemetry.enabled(True):
        jx_on = str(jax.make_jaxpr(
            lambda *a: ols._monthly_cs_ols(*a, solver="qr", guard=False)
        )(y, x, mask))
    assert jx_on == jx_off


def test_fama_macbeth_bit_identical_telemetry_on_vs_off():
    from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth

    y, x, mask = _data(seed=11)
    with telemetry.enabled(False):
        off = fama_macbeth(y, x, mask)
    with telemetry.enabled(True):
        on = fama_macbeth(y, x, mask)
    import jax

    for la, lb in zip(jax.tree.leaves(off), jax.tree.leaves(on)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# -- metrics registry -------------------------------------------------------


def test_private_counters_aggregate_and_survive_gc():
    reg = telemetry.registry()
    name = "fmrp_test_obs_agg_total"
    base = reg.collect().get(name, {}).get((), 0)
    c1 = reg.private_counter(name)
    c2 = reg.private_counter(name)
    c1.inc(3)
    c2.inc(4)
    assert (c1.value, c2.value) == (3, 4)  # per-instance views
    assert reg.collect()[name][()] - base == 7
    del c1  # CPython refcount: folds into the retained base immediately
    assert reg.collect()[name][()] - base == 7  # family total never drops


def test_shared_counter_identity_and_labels():
    reg = telemetry.registry()
    a = reg.counter("fmrp_test_obs_shared_total", site="a")
    b = reg.counter("fmrp_test_obs_shared_total", site="b")
    assert reg.counter("fmrp_test_obs_shared_total", site="a") is a
    assert a is not b
    a.inc(2)
    text = reg.to_prometheus()
    assert 'fmrp_test_obs_shared_total{site="a"} 2' in text
    assert "# TYPE fmrp_test_obs_shared_total counter" in text


def test_histogram_prometheus_rendering():
    reg = telemetry.registry()
    h = reg.private_histogram(
        "fmrp_test_obs_lat_seconds", buckets=(0.01, 0.1, 1.0)
    )
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 4 and h.sum == pytest.approx(5.555)
    text = reg.to_prometheus()
    assert 'fmrp_test_obs_lat_seconds_bucket{le="0.01"} 1' in text
    assert 'fmrp_test_obs_lat_seconds_bucket{le="+Inf"} 4' in text
    assert "fmrp_test_obs_lat_seconds_count 4" in text


def test_jax_cache_stats_shape():
    got = telemetry.jax_cache_stats()
    assert set(got) == {"entries", "bytes"}
    assert got["entries"] >= 0 and got["bytes"] >= 0
    # unreadable dir → zeros, not an exception
    assert telemetry.jax_cache_stats("/nonexistent/nowhere") == {
        "entries": 0, "bytes": 0,
    }


def test_record_trace_counts_into_registry():
    reg = telemetry.registry()

    def count():
        return reg.collect().get("fmrp_jit_traces_total", {}).get(
            (("program", "test_prog"),), 0
        )

    before = count()
    telemetry.record_trace("test_prog")
    telemetry.record_trace("test_prog")
    assert count() - before == 2


# -- exporters --------------------------------------------------------------


def _make_some_spans():
    with telemetry.enabled(True):
        with telemetry.span("alpha", cat="stage", idx=1):
            telemetry.event("tick", n=1)
            with telemetry.span("beta"):
                pass
        telemetry.event("orphan", cat="loose", z="q")


def test_jsonl_schema_roundtrip_and_determinism(tmp_path):
    _make_some_spans()
    p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    from fm_returnprediction_tpu.telemetry import export

    export.write_jsonl(p1)
    export.write_jsonl(p2)
    assert p1.read_bytes() == p2.read_bytes()  # deterministic re-export

    records = [json.loads(line) for line in p1.read_text().splitlines()]
    assert records[0]["type"] == "meta" and records[0]["schema"] == 1
    spans = [r for r in records if r["type"] == "span"]
    events = [r for r in records if r["type"] == "event"]
    assert [s["name"] for s in spans] == ["alpha", "beta"]  # start order
    assert [e["name"] for e in events] == ["orphan"]
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        for key in ("name", "cat", "trace_id", "span_id", "parent_id",
                    "ts_us", "dur_us", "thread_id", "thread_name",
                    "attrs", "events"):
            assert key in s
        if s["parent_id"] is not None:
            assert s["parent_id"] in by_id  # parent refs resolve
    assert spans[0]["events"][0]["name"] == "tick"
    assert records[-1]["type"] == "metrics"


def test_chrome_trace_is_valid_and_complete(tmp_path):
    _make_some_spans()
    from fm_returnprediction_tpu.telemetry import export

    path = export.write_chrome_trace(tmp_path / "trace.json")
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    phases = {e["ph"] for e in events}
    assert phases <= {"X", "i", "M", "C"}  # C: cost-ledger counter tracks
    complete = [e for e in events if e["ph"] == "X"
                and not e["name"].startswith("compile:")]
    assert {e["name"] for e in complete} == {"alpha", "beta"}
    for e in complete:
        assert isinstance(e["ts"], (int, float))
        assert e["dur"] >= 0
        assert {"pid", "tid", "cat", "args"} <= set(e)
    assert any(
        e["ph"] == "M" and e["name"] == "process_name" for e in events
    )
    assert any(e["ph"] == "i" and e["name"] == "orphan" for e in events)


def test_flush_writes_both_files_to_trace_dir(tmp_path):
    _make_some_spans()
    telemetry.set_trace_dir(tmp_path)
    jsonl, chrome = telemetry.flush()
    assert jsonl.exists() and chrome.exists()
    telemetry.set_trace_dir(None)
    assert telemetry.flush() is None  # unarmed: no-op


# -- integrations -----------------------------------------------------------


def test_retry_events_match_fault_plan():
    from fm_returnprediction_tpu.resilience import (
        FaultPlan,
        FaultSpec,
        RetryPolicy,
        call_with_retry,
        fault_site,
    )

    with telemetry.enabled(True):
        with FaultPlan({"obs.flaky": FaultSpec(times=2)}) as plan:
            with telemetry.span("carrier"):
                call_with_retry(
                    lambda: fault_site("obs.flaky") or True,
                    RetryPolicy(max_attempts=4, backoff_s=0.01),
                    label="obs.flaky",
                    sleep=lambda s: None,
                )
    (carrier,) = [
        s for s in telemetry.finished_spans() if s.name == "carrier"
    ]
    attempts = [e for e in carrier.events if e[0] == "retry.attempt"]
    backoffs = [e for e in carrier.events if e[0] == "retry.backoff"]
    assert len(attempts) == plan.fired["obs.flaky"] == 2
    assert len(backoffs) == 2  # one backoff per failed-but-retried attempt
    spans = [
        s for s in telemetry.finished_spans() if s.name == "retry:obs.flaky"
    ]
    assert len(spans) == 3  # two failures + the success
    assert [s.attrs["attempt"] for s in spans] == [1, 2, 3]


def test_taskgraph_failure_log_matches_trace_events(tmp_path):
    """Differential: the sqlite failure ledger and the exported JSONL
    ``task.failure`` events must describe the SAME failures (task names
    and skip/ran classification)."""
    from fm_returnprediction_tpu.taskgraph.engine import (
        PlainReporter,
        Task,
        TaskRunner,
    )

    def boom():
        raise RuntimeError("injected")

    tasks = [
        Task(name="a", actions=[boom]),
        Task(name="b", actions=[lambda: None], task_dep=["a"]),
        Task(name="c", actions=[lambda: None]),
    ]
    with telemetry.enabled(True):
        with TaskRunner(
            tasks, db_path=tmp_path / "db.sqlite", reporter=PlainReporter()
        ) as runner:
            ok = runner.run(keep_going=True)
            ledger = runner.failures()
    assert not ok
    trace_failures = {
        e["attrs"]["task"]: e["attrs"]
        for e in (
            json.loads(line)
            for line in _exported_jsonl(tmp_path).splitlines()
        )
        if e.get("type") == "event" and e.get("name") == "task.failure"
    }
    assert {row["task"] for row in ledger} == set(trace_failures) == {"a", "b"}
    assert trace_failures["a"]["ran"] is True
    assert trace_failures["b"]["ran"] is False  # dependency skip
    for row in ledger:  # error strings agree ledger↔trace
        assert trace_failures[row["task"]]["error"] == row["error"]
    # the successful independent subgraph ran under its own task span
    assert any(
        s.name == "task:c" for s in telemetry.finished_spans()
    )


def _exported_jsonl(tmp_path) -> str:
    from fm_returnprediction_tpu.telemetry import export

    return export.write_jsonl(tmp_path / "events.jsonl").read_text()


def test_checkpoint_hit_miss_events(tmp_path):
    from fm_returnprediction_tpu.resilience.checkpoint import (
        StageCheckpointer,
    )

    events = []
    with telemetry.enabled(True):
        ck = StageCheckpointer(tmp_path, "fp")
        ck.frame(
            "t", lambda: __import__("pandas").DataFrame({"a": [1.0]})
        )  # miss + save
        ck2 = StageCheckpointer(tmp_path, "fp")
        ck2.frame("t", lambda: pytest.fail("must load, not recompute"))
        events = [e["name"] for e in telemetry.standalone_events()]
    assert events == ["checkpoint.miss", "checkpoint.save", "checkpoint.hit"]


def test_serving_stats_shape_unchanged_and_spans_emitted():
    """Arming telemetry must not change the serving ``stats()`` dict shape
    (keys and value types), and must produce the request→batch→dispatch
    span chain."""
    from fm_returnprediction_tpu.serving import ERService, build_serving_state

    t, n, p = 24, 40, 4
    rng = np.random.default_rng(5)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = (0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(y, x, mask, window=12, min_periods=6)

    def run_queries(svc):
        for q in range(8):
            svc.query(t - 1, x[t - 1, q % n])
        return svc.stats()

    with ERService(state, max_batch=8, warm=True, auto_flush=False) as svc:
        with telemetry.enabled(False):
            svc.submit(t - 1, x[t - 1, 0])
            svc.batcher.drain()
        off_stats = svc.stats()
    with telemetry.enabled(True):
        with ERService(state, max_batch=8, warm=True,
                       auto_flush=False) as svc:
            svc.submit(t - 1, x[t - 1, 0])
            svc.batcher.drain()
            on_stats = svc.stats()
    assert set(off_stats) == set(on_stats)
    for k in off_stats:
        assert type(off_stats[k]) is type(on_stats[k]), k
    names = [s.name for s in telemetry.finished_spans()]
    assert "serving.batch" in names and "serving.dispatch" in names
    (batch,) = [
        s for s in telemetry.finished_spans() if s.name == "serving.batch"
    ]
    (dispatch,) = [
        s for s in telemetry.finished_spans() if s.name == "serving.dispatch"
    ]
    assert dispatch.parent_id == batch.span_id  # batch → bucket dispatch
    assert any(
        e["name"] == "serving.submit"
        for e in telemetry.standalone_events()
    )


def test_erservice_prometheus_endpoint_hook():
    from fm_returnprediction_tpu.serving import ERService, build_serving_state

    t, n, p = 24, 40, 4
    rng = np.random.default_rng(6)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = (0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(y, x, mask, window=12, min_periods=6)
    with ERService(state, max_batch=8, warm=True, auto_flush=False) as svc:
        svc.submit(t - 1, x[t - 1, 0])
        svc.batcher.drain()
        text = svc.prometheus_metrics()
    assert "fmrp_serving_executable_cache_hits_total" in text
    assert "fmrp_serving_requests_done_total" in text
    # service-level stats render as gauges (bools as 0/1, None skipped)
    assert "fmrp_serving_service_n_done 1" in text
    assert "fmrp_serving_service_degraded 0" in text
    assert "fmrp_serving_service_quarantined_months" not in text


def test_pipeline_trace_dir_end_to_end(tmp_path):
    """The acceptance flow: one ``trace_dir`` run of ``run_pipeline`` plus
    a few ERService queries produces one JSONL log and one Chrome trace
    with host spans for the pipeline stages, the serving dispatches, and
    the run root."""
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.pipeline import run_pipeline
    from fm_returnprediction_tpu.serving import ERService

    trace_dir = tmp_path / "traces"
    res = run_pipeline(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=24, n_months=42),
        make_figure=False, make_deciles=True, make_serving=True,
        compile_pdf=False, trace_dir=trace_dir,
    )
    assert (trace_dir / "events.jsonl").exists()
    assert (trace_dir / "trace.json").exists()

    # a few online queries, then close() re-flushes the same artifact
    telemetry.set_trace_dir(trace_dir)
    with telemetry.enabled(True):
        with ERService(res.serving_state, max_batch=8, warm=True) as svc:
            xq = np.zeros(res.serving_state.n_predictors, np.float32)
            for _ in range(3):
                svc.query(res.serving_state.n_months - 1, xq)

    records = [
        json.loads(line)
        for line in (trace_dir / "events.jsonl").read_text().splitlines()
    ]
    span_names = {r["name"] for r in records if r["type"] == "span"}
    for expected in ("run_pipeline", "load_raw_data", "build_panel",
                     "subset_masks", "table_1", "table_2", "decile_table",
                     "serving_state", "serving.batch", "serving.dispatch"):
        assert expected in span_names, expected
    # pipeline stages are children of the run root in ONE trace
    spans = [r for r in records if r["type"] == "span"]
    root = next(s for s in spans if s["name"] == "run_pipeline")
    t1 = next(s for s in spans if s["name"] == "table_1")
    assert t1["parent_id"] == root["span_id"]
    assert t1["trace_id"] == root["trace_id"]
    doc = json.loads((trace_dir / "trace.json").read_text())
    chrome_names = {
        e["name"] for e in doc["traceEvents"] if e["ph"] == "X"
    }
    assert "table_2" in chrome_names and "serving.dispatch" in chrome_names


def test_telemetry_off_pipeline_artifacts_bit_identical():
    """The whole synthetic pipeline: telemetry armed vs disarmed emits
    bit-identical tables (the tracer is pure observation)."""
    import pandas as pd

    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.pipeline import run_pipeline

    kw = dict(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=20, n_months=36),
        make_figure=False, make_deciles=False, make_serving=False,
        compile_pdf=False,
    )
    with telemetry.enabled(False):
        off = run_pipeline(**kw)
    with telemetry.enabled(True):
        on = run_pipeline(**kw)
    pd.testing.assert_frame_equal(on.table_1, off.table_1)
    pd.testing.assert_frame_equal(on.table_2, off.table_2)


# -- per-process identity (ISSUE 13) -----------------------------------------


def test_process_identity_precedence(monkeypatch):
    """explicit set_process_index > FMRP_PROC_INDEX (fleet replica
    children) > FMRP_DIST_PROC_ID (exchange workers) > None; resolved
    LIVE (the repo-wide env-knob discipline)."""
    from fm_returnprediction_tpu.telemetry import identity

    monkeypatch.delenv("FMRP_PROC_INDEX", raising=False)
    monkeypatch.delenv("FMRP_DIST_PROC_ID", raising=False)
    identity.set_process_index(None)
    assert identity.process_index() is None
    assert identity.process_suffix() == ""
    monkeypatch.setenv("FMRP_DIST_PROC_ID", "3")
    assert identity.process_index() == 3
    monkeypatch.setenv("FMRP_PROC_INDEX", "7")
    assert identity.process_index() == 7  # generic identity wins
    identity.set_process_index(2)
    try:
        assert identity.process_index() == 2  # the bootstrap's pin wins
        assert identity.process_suffix() == "[p2]"
    finally:
        identity.set_process_index(None)


def test_prometheus_export_carries_process_index_only_when_armed(
    monkeypatch,
):
    """Armed: every exported series gains process_index="k" so merged
    multi-process scrapes stay attributable. Unarmed: the export is
    byte-identical to the historical single-process text."""
    from fm_returnprediction_tpu.telemetry import identity
    from fm_returnprediction_tpu.telemetry.metrics import MetricsRegistry

    monkeypatch.delenv("FMRP_PROC_INDEX", raising=False)
    monkeypatch.delenv("FMRP_DIST_PROC_ID", raising=False)
    identity.set_process_index(None)
    reg = MetricsRegistry()
    reg.counter("fmrp_test_ident_total", help="h", route="a").inc(2)
    reg.gauge("fmrp_test_ident_gauge", help="h").set(1.5)
    unarmed = reg.to_prometheus()
    assert "process_index" not in unarmed
    identity.set_process_index(4)
    try:
        armed = reg.to_prometheus()
    finally:
        identity.set_process_index(None)
    for line in armed.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        assert 'process_index="4"' in line, line
    # disarming restores the byte-identical historical export
    assert reg.to_prometheus() == unarmed


def test_jsonl_meta_and_chrome_trace_carry_identity(tmp_path, monkeypatch):
    from fm_returnprediction_tpu.telemetry import export, identity

    monkeypatch.delenv("FMRP_PROC_INDEX", raising=False)
    monkeypatch.delenv("FMRP_DIST_PROC_ID", raising=False)
    identity.set_process_index(None)
    with telemetry.enabled(True):
        with telemetry.span("ident.work", cat="test"):
            pass
        meta_off = json.loads(
            export.write_jsonl(tmp_path / "off.jsonl").read_text()
            .splitlines()[0]
        )
        assert "process_index" not in meta_off
        name_off = export.chrome_trace_events()[0]["args"]["name"]
        assert name_off == "fmrp-host"
        identity.set_process_index(5)
        try:
            meta_on = json.loads(
                export.write_jsonl(tmp_path / "on.jsonl").read_text()
                .splitlines()[0]
            )
            assert meta_on["process_index"] == 5
            name_on = export.chrome_trace_events()[0]["args"]["name"]
            assert name_on == "fmrp-host[p5]"
        finally:
            identity.set_process_index(None)


def test_jax_cache_stats_counts_files_only(tmp_path):
    """``entries`` and ``bytes`` must read the SAME isfile-filtered
    list: a subdirectory (or transient non-file) counted in entries but
    not bytes made entry-growth-with-zero-byte-growth look like the
    compile cache gaining empty entries."""
    from fm_returnprediction_tpu.telemetry import jax_cache_stats

    (tmp_path / "a.bin").write_bytes(b"x" * 10)
    (tmp_path / "b.bin").write_bytes(b"y" * 5)
    (tmp_path / "subdir").mkdir()
    got = jax_cache_stats(str(tmp_path))
    assert got == {"entries": 2, "bytes": 15}
    assert jax_cache_stats(str(tmp_path / "missing")) == {
        "entries": 0, "bytes": 0,
    }
