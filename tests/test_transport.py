"""Zero-copy shared-memory data plane (ISSUE 15).

Four layers of evidence, cheapest first:

- the RING itself: seq/commit protocol (order, wraparound), torn frames
  reading as ABSENT, ring-full backpressure as a typed stall→error;
- the FRAME grammar: submit/ack/result round-trips, including the cold
  paths (non-int months, pickled rows, exception tails) and the
  ``DegradedQuote`` disclosure columns;
- the FLEET data plane: shm-vs-socket-vs-thread bit-identical quotes
  (fleet-of-1 and fleet-of-N), ring-full surfacing as the retriable
  ``ServiceOverloadError``, and the journal replaying CLEAN through a
  mid-load ``hard_crash`` on the shm path;
- the MULTIPROC GRID plane: mapped-segment stats return bit-identical
  to the pickled-frames oracle, with the exchange byte bill collapsed.
"""

import os
import struct
import threading
import time

import numpy as np
import pytest

from fm_returnprediction_tpu.parallel.shm import (
    HEADER_BYTES,
    RingFullError,
    ShmRing,
    attach_ring,
    shm_available,
    transport_instruments,
)
from fm_returnprediction_tpu.serving import shm as fleet_shm

pytestmark = [
    pytest.mark.transport,
    pytest.mark.skipif(not shm_available(),
                       reason="POSIX shared memory unavailable here"),
]


# -- the ring ---------------------------------------------------------------


def test_ring_roundtrip_order_and_wraparound():
    ring = ShmRing(create=True, slots=8, slot_bytes=256)
    try:
        reader = attach_ring(ring.name)
        payloads = [f"frame-{i}".encode() * (i % 3 + 1) for i in range(13)]
        # more frames than slots: the ring must wrap and stay ordered
        got = []
        for i, p in enumerate(payloads):
            ring.send(p, timeout_s=1.0)
            if i % 2:  # drain irregularly to exercise partial occupancy
                got.append(reader.recv(timeout_s=1.0))
        while len(got) < len(payloads):
            got.append(reader.recv(timeout_s=1.0))
        assert got == payloads
        reader.close()
    finally:
        ring.close()


def test_torn_frame_reads_as_absent_until_committed():
    """A writer that dies mid-frame leaves the commit word stale — the
    reader must see NOTHING (not a garbage frame), which is what lets
    journal recovery treat in-flight requests as cleanly absent."""
    ring = ShmRing(create=True, slots=4, slot_bytes=256)
    try:
        reader = attach_ring(ring.name)
        # white-box torn write: payload + length land, commit does NOT
        # (the exact state a crash between those stores leaves behind)
        payload = b"half-written"
        off = HEADER_BYTES  # slot 0 = seq 1
        ring._buf[off + 16:off + 16 + len(payload)] = payload
        struct.pack_into("<I", ring._buf, off + 8, len(payload))
        assert reader.recv(timeout_s=0.05) is None  # absent, not torn
        # the commit store is what makes the frame exist
        struct.pack_into("<Q", ring._buf, off, 1)
        assert reader.recv(timeout_s=1.0) == payload
        reader.close()
    finally:
        ring.close()


def test_ring_full_stalls_then_raises_typed():
    inst = transport_instruments("shm", "ringtest")
    stalls0 = inst["stalls"].value
    ring = ShmRing(create=True, slots=2, slot_bytes=128,
                   instruments=inst)
    try:
        ring.send(b"a", timeout_s=0.2)
        ring.send(b"b", timeout_s=0.2)
        t0 = time.monotonic()
        with pytest.raises(RingFullError):
            ring.send(b"c", timeout_s=0.08)  # no reader: must stall+raise
        assert time.monotonic() - t0 >= 0.07
        assert inst["stalls"].value == stalls0 + 1
    finally:
        ring.close()


def test_oversized_frame_rejected():
    ring = ShmRing(create=True, slots=2, slot_bytes=64)
    try:
        with pytest.raises(ValueError):
            ring.send(b"x" * 256, timeout_s=0.1)
    finally:
        ring.close()


# -- the frame grammar ------------------------------------------------------


def test_submit_frame_roundtrip_hot_and_cold_paths():
    rows = [
        (1, 7, np.arange(4, dtype=np.float32)),            # hot f32
        (2, 9, np.arange(3, dtype=np.float64) * 1.5),      # f64 column
        (3, "2001-01", np.ones(2, dtype=np.float32)),      # month tail
        (4, 11, [1.0, 2.0]),                               # pickled row
    ]
    kind, back = fleet_shm.unpack_frame(fleet_shm.pack_submit(rows))
    assert kind == fleet_shm.KIND_SUBMIT
    for (rid, month, x), (rid2, month2, x2) in zip(rows, back):
        assert rid2 == rid and month2 == month
        if isinstance(x, np.ndarray):
            assert x2.dtype == x.dtype
            assert np.array_equal(x2, x)
        else:
            assert x2 == x


def test_submit_frame_single_row_fast_path_matches_layout():
    row = np.arange(5, dtype=np.float32)
    frame = fleet_shm.pack_submit([(42, 13, row)])
    kind, back = fleet_shm.unpack_frame(frame)
    assert kind == fleet_shm.KIND_SUBMIT
    (rid, month, x), = back
    assert (rid, month) == (42, 13)
    assert x.dtype == np.float32 and np.array_equal(x, row)


def test_ack_and_result_frame_roundtrip_with_degraded_columns():
    from fm_returnprediction_tpu.serving.brownout import DegradedQuote

    ack = fleet_shm.pack_ack(
        [5, 6], [fleet_shm.STATUS_QUEUE_FULL, fleet_shm.STATUS_ERROR],
        {0: {"message": "full", "queue_depth": 3, "max_queue": 4},
         1: {"exc": None, "error": "KeyError(99)"}},
    )
    kind, rows = fleet_shm.unpack_frame(ack)
    assert kind == fleet_shm.KIND_ACK
    assert rows[0][:2] == (5, fleet_shm.STATUS_QUEUE_FULL)
    assert rows[0][2]["queue_depth"] == 3
    assert rows[1][2]["error"] == "KeyError(99)"

    dq = DegradedQuote(0.25, route="coreset", precision="f32",
                       m=8, err_bound=0.125)
    res = fleet_shm.pack_results([
        (7, True, 0.5),
        (8, True, dq),
        (9, False, KeyError(123)),
    ])
    kind, rows = fleet_shm.unpack_frame(res)
    assert kind == fleet_shm.KIND_RESULT
    assert rows[0] == (7, True, 0.5)
    rid, ok, val = rows[1]
    assert ok and float(val) == 0.25
    # the disclosure the socket mode's float() coercion used to strip
    assert isinstance(val, DegradedQuote)
    assert (val.route, val.precision, val.m, val.err_bound) == (
        "coreset", "f32", 8, 0.125
    )
    rid, ok, payload = rows[2]
    assert not ok and "KeyError" in payload["error"]


def test_result_frame_all_ok_fast_path():
    res = fleet_shm.pack_results([(i, True, float(i) / 7) for i in range(9)])
    kind, rows = fleet_shm.unpack_frame(res)
    assert rows == [(i, True, float(i) / 7) for i in range(9)]


# -- channel backpressure ---------------------------------------------------


def test_channel_ring_full_surfaces_typed_retriable_overload():
    from fm_returnprediction_tpu.resilience.errors import (
        ServiceOverloadError,
    )

    acks = []
    inst = transport_instruments("shm", "chantest")
    stalls0 = inst["stalls"].value
    chan = fleet_shm.ShmReplicaChannel(
        on_ack=lambda rid, st, ev: acks.append((rid, st, ev)),
        on_results=lambda rows: None,
        on_dead=lambda why: None,
        replica_id="chantest", slots=2, slot_bytes=2048,
        send_timeout_s=0.05, instruments=inst,
    )
    try:
        row = np.ones(4, dtype=np.float32)
        # no consumer on the request ring: the first sends fill it, the
        # next stalls past its deadline and every row of that strip must
        # come back as the fleet's typed retriable 429
        for i in range(3):
            chan.submit_row(i, 0, row)
        assert len(acks) >= 1
        rid, st, ev = acks[-1]
        exc = ev["overload"]
        assert isinstance(exc, ServiceOverloadError)
        assert exc.reason == "transport_ring_full"
        assert exc.retry_after_s > 0
        assert inst["stalls"].value > stalls0
    finally:
        chan.stop()


# -- in-process data-plane serve loop ---------------------------------------


def _tiny_state(t=36, n=80, p=4, seed=3):
    from fm_returnprediction_tpu.serving import build_serving_state

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = (rng.standard_normal(p) * 0.05).astype(np.float32)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(y, x, mask, window=t // 2,
                                min_periods=t // 4)
    months = np.nonzero(state.have_coef())[0]
    return state, months, rng


def test_serve_data_plane_in_process_and_torn_strip_ignored():
    from fm_returnprediction_tpu.serving.service import ERService

    state, months, rng = _tiny_state()
    service = ERService(state, max_batch=8, max_latency_ms=0.5)
    req = ShmRing(create=True, slots=8, slot_bytes=4096)
    resp = ShmRing(create=True, slots=8, slot_bytes=4096)
    stop = threading.Event()
    th = threading.Thread(
        target=fleet_shm.serve_data_plane,
        args=(service, attach_ring(req.name), attach_ring(resp.name), stop),
        daemon=True,
    )
    th.start()
    try:
        month = int(months[0])
        row = rng.standard_normal(4).astype(np.float32)
        want = service.query(month, row)
        req.send(fleet_shm.pack_submit([(1, month, row)]), timeout_s=1.0)
        frame = resp.recv(timeout_s=5.0)
        assert frame is not None
        kind, rows = fleet_shm.unpack_frame(frame)
        assert kind == fleet_shm.KIND_RESULT
        assert rows[0][0] == 1 and rows[0][1] is True
        assert rows[0][2] == want  # same service, same bits
        # a POISON row (ragged list — np.asarray raises) must fail
        # ALONE: its strip-mate still gets its quote, the ragged row an
        # ACK-reject, and the serve thread survives (an unguarded
        # asarray would kill it and blackhole the replica)
        req.send(fleet_shm.pack_submit([
            (2, month, [[1.0, 2.0], [3.0]]),
            (3, month, row),
        ]), timeout_s=1.0)
        got = {}
        while len(got) < 2:
            frame = resp.recv(timeout_s=5.0)
            assert frame is not None
            kind, frame_rows = fleet_shm.unpack_frame(frame)
            if kind == fleet_shm.KIND_RESULT:
                for rid, ok, val in frame_rows:
                    got[rid] = (kind, ok, val)
            else:
                for rid, status, ev in frame_rows:
                    got[rid] = (kind, status, ev)
        assert got[3] == (fleet_shm.KIND_RESULT, True, want)
        kind2, status2, ev2 = got[2]
        assert kind2 == fleet_shm.KIND_ACK
        assert status2 == fleet_shm.STATUS_ERROR
        assert "array-like" in ev2["error"] or "1-D" in ev2["error"]
        # a torn strip (commit word never written) must be ABSENT: no
        # response, no crash, the loop stays alive for the stop event
        payload = fleet_shm.pack_submit([(4, month, row)])
        seq = req._wseq + 1
        off = req._slot_off(seq)
        req._buf[off + 16:off + 16 + len(payload)] = payload
        struct.pack_into("<I", req._buf, off + 8, len(payload))
        assert resp.recv(timeout_s=0.3) is None
    finally:
        stop.set()
        th.join(timeout=5)
        assert not th.is_alive()
        service.close()
        req.close()
        resp.close()


# -- batch submit (the serve loop's absorption path) -------------------------


def test_batcher_submit_many_matches_submit_semantics():
    from fm_returnprediction_tpu.serving.batcher import (
        MicroBatcher,
        QueueFullError,
    )

    done = []
    b = MicroBatcher(lambda m, x, v: np.asarray(m, np.float64),
                     max_batch=4, max_latency_ms=50.0, max_queue=3,
                     auto_flush=False, n_predictors=3)
    rows = [
        (0, np.ones(3, np.float32)),
        (1, np.ones(2, np.float32)),   # wrong width: fails alone
        (2, np.ones(3, np.float32)),
        (3, np.ones(3, np.float32)),
        (4, np.ones(3, np.float32)),   # queue (3) full by now
    ]
    out = b.submit_many(rows)
    kinds = [k for k, _ in out]
    assert kinds == ["ok", "err", "ok", "ok", "err"]
    assert isinstance(out[1][1], ValueError)
    assert isinstance(out[4][1], QueueFullError)
    assert out[4][1].max_queue == 3
    b.drain()
    assert [out[i][1].result(timeout=5) for i in (0, 2, 3)] == [0, 2, 3]
    b.close()
    assert done == []


def test_service_submit_many_unknown_month_fails_alone():
    from fm_returnprediction_tpu.serving.service import ERService

    state, months, rng = _tiny_state()
    service = ERService(state, max_batch=8, auto_flush=False)
    try:
        row = rng.standard_normal(4).astype(np.float32)
        out = service.submit_many([
            (int(months[0]), row),
            (10 ** 9, row),             # unknown month → KeyError slot
            (int(months[-1]), row),
        ])
        assert [k for k, _ in out] == ["ok", "err", "ok"]
        assert isinstance(out[1][1], KeyError)
        service.batcher.drain()
        assert np.isfinite(out[0][1].result(timeout=5))
        assert np.isfinite(out[2][1].result(timeout=5))
    finally:
        service.close()


# -- the process fleet over both transports ---------------------------------


def _fleet_quotes(fleet, months, rows):
    return np.asarray([
        fleet.query(int(m), r) for m, r in zip(months, rows)
    ])


@pytest.mark.fleet
def test_fleet_quotes_bit_identical_thread_socket_shm(tmp_path):
    """THE transport differential: the same queries through thread
    replicas, a socket process fleet (fleet-of-1), and shm process
    fleets of 1 and 2 — every float bit-identical, every journal
    replaying clean."""
    from fm_returnprediction_tpu.serving import ServingFleet, replay_journal

    state, months, rng = _tiny_state(t=48, n=120, p=4)
    k = 24
    qm = months[rng.integers(0, len(months), k)]
    qx = rng.standard_normal((k, 4)).astype(np.float32)

    fleets = (
        ("thread", dict(replica_mode="thread")),
        ("socket1", dict(replica_mode="process", transport="socket")),
        ("shm1", dict(replica_mode="process", transport="shm")),
        ("shm2", dict(replica_mode="process", transport="shm")),
    )
    vals = {}
    for name, kw in fleets:
        n_rep = 2 if name.endswith("2") else 1
        journal = tmp_path / f"{name}.jsonl"
        fleet = ServingFleet(state, n_rep, max_batch=16,
                             max_latency_ms=1.0, journal=journal, **kw)
        try:
            if kw["replica_mode"] == "process":
                st = fleet.stats()
                assert st["transport"] in (kw.get("transport"),)
            vals[name] = _fleet_quotes(fleet, qm, qx)
        finally:
            fleet.close()
        assert replay_journal(journal).clean, name
    base = vals["thread"]
    assert np.isfinite(base).all()
    for name in ("socket1", "shm1", "shm2"):
        assert np.array_equal(base, vals[name]), name


@pytest.mark.fleet
@pytest.mark.chaos
def test_shm_fleet_hard_crash_journal_replays_clean(tmp_path):
    """The acceptance composition: requests in flight on the shm rings,
    the router hard-crashes (journal abandoned, children killed, any
    mid-send frame left torn-by-construction), and recovery closes the
    session out to a CLEAN replay — zero dropped, zero duplicated."""
    from fm_returnprediction_tpu.serving import ServingFleet, replay_journal

    state, months, rng = _tiny_state(t=48, n=120, p=4)
    journal = tmp_path / "crash.jsonl"
    fleet = ServingFleet(state, 2, replica_mode="process", transport="shm",
                         max_batch=16, max_latency_ms=5.0, journal=journal)
    qx = rng.standard_normal((40, 4)).astype(np.float32)
    # warm, then pile submits on the rings and crash with them in flight
    fleet.query(int(months[0]), qx[0])
    futs = [fleet.submit(int(months[i % len(months)]), qx[i])
            for i in range(40)]
    fleet.hard_crash()
    del futs
    dirty = replay_journal(journal)
    assert not dirty.clean  # admitted-no-terminal requests dangle
    recovered, report = ServingFleet.recover(
        journal, state=state, replica_mode="thread",
        max_batch=16, auto_flush=False,
    )
    try:
        assert report.journal.replay_clean
        assert len(report.journal.recovered) > 0  # real in-flight closed out
        final = replay_journal(journal)
        assert final.clean
        assert report.rotated_to is not None
        rotated = replay_journal(report.rotated_to)
        assert rotated.clean
        assert len(rotated.dropped) == 0 and len(rotated.duplicated) == 0
        # and the recovered fleet quotes
        f = recovered.submit(int(months[0]), qx[0])
        recovered.flush_all()
        assert np.isfinite(f.result(timeout=5))
    finally:
        recovered.close()


# -- the multiproc grid over both transports --------------------------------


@pytest.mark.multiprocess
def test_multiproc_grid_shm_vs_frames_bit_identical():
    """Leg (b): mapped-segment stats return must equal the pickled
    frames oracle (same rank-ordered fold → bit-identical, stronger
    than the PR-14 parity tolerances it is allowed), with the exchange
    byte bill collapsed to control frames."""
    from fm_returnprediction_tpu.specgrid.multiproc import (
        SpecGridWorkerPool,
    )

    rng = np.random.default_rng(7)
    t, n, p = 24, 64, 6
    y = np.where(rng.random((t, n)) > 0.2,
                 rng.standard_normal((t, n)), np.nan).astype(np.float32)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    x[rng.random((t, n, p)) < 0.05] = np.nan
    universes = rng.random((2, t, n)) > 0.3
    uidx = np.array([0, 1, 0])
    col_sel = np.zeros((3, p), bool)
    col_sel[0, :3] = True
    col_sel[1, :5] = True
    col_sel[2, :] = True
    window = np.ones((3, t), bool)

    stats, merge_bytes = {}, {}
    for transport in ("frames", "shm"):
        pool = SpecGridWorkerPool(2, y, x, universes, transport=transport)
        try:
            s1 = pool.contract(uidx, col_sel, window)
            s2 = pool.contract(uidx, col_sel, window)  # warm: cached
            #                               center + reused segments
            for a, b in zip(s1[:5], s2[:5]):
                assert np.array_equal(np.asarray(a), np.asarray(b),
                                      equal_nan=True)
            stats[transport] = s1
            merge_bytes[transport] = pool.last_merge_bytes
            if transport == "shm":
                assert pool.last_shm_bytes > 0
        finally:
            pool.close()
    for a, b in zip(stats["frames"][:6], stats["shm"][:6]):
        assert np.array_equal(np.asarray(a), np.asarray(b),
                              equal_nan=True)
    # the whole point: stats leave the exchange (≥5× here; ≥10× at
    # bench shape where the gram payload dominates the fixed overhead)
    assert merge_bytes["shm"] * 5 <= merge_bytes["frames"]


# -- knob resolution --------------------------------------------------------


def test_transport_resolution_knobs(monkeypatch):
    from fm_returnprediction_tpu.specgrid.multiproc import (
        resolve_grid_transport,
    )

    monkeypatch.delenv("FMRP_FLEET_TRANSPORT", raising=False)
    monkeypatch.delenv("FMRP_GRID_TRANSPORT", raising=False)
    assert fleet_shm.resolve_fleet_transport() == "shm"  # auto, shm works
    assert fleet_shm.resolve_fleet_transport("socket") == "socket"
    assert resolve_grid_transport() == "shm"
    assert resolve_grid_transport("frames") == "frames"
    monkeypatch.setenv("FMRP_FLEET_TRANSPORT", "socket")
    monkeypatch.setenv("FMRP_GRID_TRANSPORT", "frames")
    assert fleet_shm.resolve_fleet_transport() == "socket"
    assert resolve_grid_transport() == "frames"
    assert fleet_shm.resolve_fleet_transport("shm") == "shm"  # arg wins
    assert resolve_grid_transport("shm") == "shm"
    with pytest.raises(ValueError):
        fleet_shm.resolve_fleet_transport("carrier-pigeon")
    with pytest.raises(ValueError):
        resolve_grid_transport("carrier-pigeon")
