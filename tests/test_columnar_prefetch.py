"""The cold-ingest overlap queue (``data.columnar._prefetched``): order
preservation, exact parity with the serial loop, exception propagation,
and clean shutdown when the consumer bails early."""

import time

import numpy as np
import pytest

from fm_returnprediction_tpu.data.columnar import (
    _prefetched,
    read_filtered_columns,
    resolve_prefetch_depth,
)

pytestmark = pytest.mark.kernels


def test_depth_resolution(monkeypatch):
    monkeypatch.delenv("FMRP_INGEST_PREFETCH", raising=False)
    assert resolve_prefetch_depth() == 2
    monkeypatch.setenv("FMRP_INGEST_PREFETCH", "0")
    assert resolve_prefetch_depth() == 0
    monkeypatch.setenv("FMRP_INGEST_PREFETCH", "5")
    assert resolve_prefetch_depth() == 5
    monkeypatch.setenv("FMRP_INGEST_PREFETCH", "nope")
    assert resolve_prefetch_depth() == 0      # unparseable → serial, safely
    assert resolve_prefetch_depth(3) == 3     # arg beats env
    assert resolve_prefetch_depth(-1) == 0


def test_order_preserved_and_depth_zero_serial():
    items = list(range(57))
    assert list(_prefetched(iter(items), 3)) == items
    assert list(_prefetched(iter(items), 0)) == items
    assert list(_prefetched(iter([]), 2)) == []


def test_reader_exception_propagates():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("disk gone")

    out = []
    with pytest.raises(RuntimeError, match="disk gone"):
        for v in _prefetched(gen(), 2):
            out.append(v)
    assert out == [1, 2]


def test_early_consumer_exit_stops_reader():
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    it = _prefetched(gen(), 2)
    got = [next(it), next(it)]
    it.close()                                 # consumer bails early
    time.sleep(0.2)
    n = len(produced)
    time.sleep(0.2)
    assert len(produced) == n                  # reader actually stopped
    assert got == [0, 1]
    # bounded read-ahead: the reader never ran far past the queue depth
    assert n <= 2 + 2 + 2


def test_filtered_read_parity_serial_vs_prefetched(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    n = 10_000
    flags = rng.choice(["10", "11", "20"], n)
    exch = rng.choice(["N", "A", "Q"], n)
    table = pa.table({
        "shrcd": pa.array(flags).dictionary_encode(),
        "exchcd": pa.array(exch).dictionary_encode(),
        "ret": rng.standard_normal(n),
        "permno": rng.integers(1, 500, n),
    })
    path = tmp_path / "strip.parquet"
    pq.write_table(table, path)

    kw = dict(
        value_columns=["ret", "permno"],
        flag_spec={"shrcd": ["10", "11"], "exchcd": ["N", "A", "Q"]},
        bool_columns={"exchcd": ["N"]},
        batch_rows=700,                        # many batches through the queue
    )
    serial = read_filtered_columns(path, prefetch=0, **kw)
    overlapped = read_filtered_columns(path, prefetch=3, **kw)
    assert serial.keys() == overlapped.keys()
    for k in serial:
        np.testing.assert_array_equal(serial[k], overlapped[k], err_msg=k)
    keep = np.isin(flags, ["10", "11"])
    np.testing.assert_allclose(
        serial["ret"], np.asarray(table["ret"])[keep]
    )
    np.testing.assert_array_equal(serial["exchcd"], exch[keep] == "N")
