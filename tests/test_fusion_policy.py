"""Unit contract of ``reporting.fusion`` — the policy that decides between
the fused subset-vmapped reporting programs and per-cell dispatches (the
real-shape TPU compile fix). The end-to-end bit-identity of the two routes
is covered in ``test_reporting.py::test_fusion_split_routes_match_fused``;
here: the footprint model, the budget boundary, and the env override.
"""

from fm_returnprediction_tpu.reporting.fusion import (
    fuse_budget_bytes,
    fuse_over_subsets,
    stacked_design_bytes,
)


def test_footprint_model():
    # n_subsets * t * n * (p + 2) * itemsize, exactly
    assert stacked_design_bytes(3, 600, 22000, 14, 4) == 3 * 600 * 22000 * 16 * 4


def test_default_budget_splits_real_shape_and_fuses_toy():
    # real CRSP shape (~2.5 GB) must split; the toy bench shape (~92 MB)
    # and every test shape must fuse — the two regimes the default budget
    # was chosen to separate
    assert not fuse_over_subsets(3, 600, 22000, 14, 4)
    assert fuse_over_subsets(3, 600, 800, 14, 4)
    assert fuse_over_subsets(3, 84, 40, 14, 8)


def test_env_override(monkeypatch):
    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", "0")
    assert fuse_budget_bytes() == 0
    assert not fuse_over_subsets(1, 1, 1, 1, 4)  # any footprint > 0 splits

    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", "1048576")  # 1 TiB
    assert fuse_over_subsets(3, 600, 22000, 14, 4)


def test_malformed_override_warns_and_uses_default(monkeypatch, recwarn):
    import warnings

    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", "512MB")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert fuse_budget_bytes() == 512.0 * 2**20  # falls back, not raises
    assert any("FMRP_FUSE_SUBSETS_MB" in str(w.message) for w in caught)


def test_negative_override_clamps_to_force_split(monkeypatch):
    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", "-16")
    assert fuse_budget_bytes() == 0  # explicit, not silent: acts like 0
    assert not fuse_over_subsets(1, 1, 1, 1, 4)


def test_budget_boundary_is_inclusive(monkeypatch):
    bytes_needed = stacked_design_bytes(2, 10, 100, 3, 4)
    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", str(bytes_needed / 2**20))
    assert fuse_over_subsets(2, 10, 100, 3, 4)  # == budget → fuse
    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB",
                       str((bytes_needed - 1) / 2**20))
    assert not fuse_over_subsets(2, 10, 100, 3, 4)
