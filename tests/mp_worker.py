"""Worker for the two-process ``jax.distributed`` test (test_multiprocess.py).

Each process pins CPU with 2 virtual local devices, brings up the
distributed runtime through ``initialize_multihost`` (the production init
path), builds the default months×firms mesh — which on 2 processes × 2
local devices is the (2, 2) hierarchy with one mesh ROW per process, the
pod layout — runs one ``fama_macbeth_hier`` step on a shared seeded panel,
and checks it against the plain single-device ``fama_macbeth`` computed
locally. Prints ``MP_OK <process_id>`` as the success marker the parent
asserts on.

Usage: python mp_worker.py <process_id> <num_processes> <port>
"""

import os
import sys

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

from fm_returnprediction_tpu.parallel.multihost import (  # noqa: E402
    initialize_multihost,
)

got = initialize_multihost(
    coordinator_address=f"localhost:{port}", num_processes=nprocs, process_id=pid
)
assert got == (pid, nprocs), f"process coords {got} != {(pid, nprocs)}"
# idempotent second call must not raise and must return the same coords
assert initialize_multihost(
    coordinator_address=f"localhost:{port}", num_processes=nprocs, process_id=pid
) == (pid, nprocs)

import jax  # noqa: E402
import numpy as np  # noqa: E402

assert jax.process_count() == nprocs
assert len(jax.devices()) == 2 * nprocs, "global device set must span processes"

from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth  # noqa: E402
from fm_returnprediction_tpu.parallel import fama_macbeth_hier  # noqa: E402

rng = np.random.default_rng(7)  # same seed everywhere: identical global data
t, n, p = 18, 12, 3
x = rng.standard_normal((t, n, p))
y = x @ (0.1 * rng.standard_normal(p)) + 0.05 * rng.standard_normal((t, n))
mask = rng.random((t, n)) > 0.2
y = np.where(mask, y, np.nan)

# The production mesh policy: with process_count>1 this must dispatch to
# the months×firms hierarchy (one row per process) regardless of
# MESH_DEVICES — the branch only a real multi-process run can exercise.
from fm_returnprediction_tpu.parallel import pipeline_mesh  # noqa: E402

mesh = pipeline_mesh()
assert mesh is not None and mesh.axis_names == ("months", "firms"), mesh
assert mesh.shape == {"months": nprocs, "firms": 2}, mesh.shape
row_procs = {d.process_index for d in mesh.devices[pid]}
assert row_procs == {pid}, f"mesh row {pid} spans processes {row_procs}"

cs, fm = fama_macbeth_hier(y, x, mask, mesh=mesh)
_, ref = jax.jit(fama_macbeth)(y, x, mask)  # local single-device oracle

np.testing.assert_allclose(
    np.asarray(fm.coef), np.asarray(ref.coef), rtol=1e-8, atol=1e-10
)
np.testing.assert_allclose(
    np.asarray(fm.tstat), np.asarray(ref.tstat), rtol=1e-8, atol=1e-10
)
assert cs.slopes.shape == (t, p)  # (T, P): month padding trimmed

# Bootstrap across the process boundary: typed PRNG keys cannot take the
# host-value-checked device_put route onto a non-addressable sharding (it
# is rejected outright) — place_global's key_data/wrap_key_data path must
# carry them. NaN months in the replicated slopes exercise the NaN-safe
# placement too.
from fm_returnprediction_tpu.parallel import as_flat_mesh, block_bootstrap_se  # noqa: E402

slope_valid = cs.month_valid[:, None] & np.isfinite(np.asarray(cs.slopes))
res = block_bootstrap_se(
    cs.slopes, slope_valid, jax.random.key(0), n_replicates=8,
    mesh=as_flat_mesh(mesh, axis_name="boot"),
)
assert np.isfinite(np.asarray(res.se)).all(), "non-finite bootstrap SEs"

print(f"MP_OK {pid}", flush=True)
