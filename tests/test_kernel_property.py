"""Property-based differential tests for the econometric core.

``monthly_cs_ols`` (the hot kernel) against a per-month numpy ``lstsq``
transcription of the reference's loop (``src/regressions.py:43-72`` —
statsmodels' pinv solve is the same minimum-norm solution), and
``nw_mean_se`` against a fresh inline transcription of the reference's
Newey-West formula with the non-textbook ``1 − k/n`` Bartlett weight
(``src/regressions.py:78-100``). Random sizes, masks, NaN patterns and
degenerate months (n ≤ P) come from hypothesis rather than fixed seeds.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # tier-1 must COLLECT cleanly without the optional dep
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.newey_west import nw_mean_se
from fm_returnprediction_tpu.ops.ols import monthly_cs_ols


@st.composite
def _ols_cases(draw):
    t = draw(st.integers(min_value=1, max_value=8))
    p = draw(st.integers(min_value=1, max_value=4))
    # n around the reference's n >= P+1 gate, including below it
    n = draw(st.integers(min_value=1, max_value=4 * (p + 2)))
    nan_frac = draw(st.floats(min_value=0.0, max_value=0.3))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    return t, n, p, nan_frac, seed


@settings(max_examples=25, deadline=None)
@given(_ols_cases())
def test_monthly_cs_ols_matches_numpy_lstsq(case):
    t, n, p, nan_frac, seed = case
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p))
    y = rng.standard_normal((t, n))
    y[rng.random((t, n)) < nan_frac] = np.nan
    mask = rng.random((t, n)) < 0.9

    cs = monthly_cs_ols(jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask))

    for ti in range(t):
        rows = mask[ti] & np.isfinite(y[ti]) & np.isfinite(x[ti]).all(axis=1)
        nv = int(rows.sum())
        if nv < p + 1:  # the reference's skip guard
            assert not bool(cs.month_valid[ti])
            continue
        assert bool(cs.month_valid[ti])
        design = np.concatenate([np.ones((nv, 1)), x[ti][rows]], axis=1)
        beta, _, _, _ = np.linalg.lstsq(design, y[ti][rows], rcond=None)
        got = np.concatenate(
            [[np.asarray(cs.intercept)[ti]], np.asarray(cs.slopes)[ti]]
        )
        np.testing.assert_allclose(got, beta, rtol=1e-6, atol=1e-8)

        resid = y[ti][rows] - design @ beta
        sst = ((y[ti][rows] - y[ti][rows].mean()) ** 2).sum()
        want_r2 = 1.0 - (resid @ resid) / sst if sst > 0 else 0.0
        np.testing.assert_allclose(
            float(np.asarray(cs.r2)[ti]), want_r2, rtol=1e-6, atol=1e-8
        )


def test_qr_solver_matches_lstsq_on_near_singular_months():
    """The default "qr" solver must reproduce the direct SVD lstsq solution
    in the boundary regime the reference's gate admits (n = P+1, cond ~ 1e6)
    — the same bar the sharded TSQR path is held to."""
    rng = np.random.default_rng(7)
    t, n, p = 10, 64, 5
    x = rng.standard_normal((t, n, p))
    y = rng.standard_normal((t, n))
    mask = np.ones((t, n), bool)
    for ti in range(0, t, 2):
        mask[ti, p + 1:] = False
        base = rng.standard_normal(p)
        for r in range(p + 1):
            x[ti, r] = base + 1e-6 * rng.standard_normal(p)
    y = np.where(mask, y, np.nan)

    qr = monthly_cs_ols(jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask),
                        solver="qr")
    sv = monthly_cs_ols(jnp.asarray(y), jnp.asarray(x), jnp.asarray(mask),
                        solver="lstsq")
    assert np.asarray(sv.month_valid).all()
    drift = np.abs(np.asarray(qr.slopes) - np.asarray(sv.slopes)).max()
    assert drift < 1e-6, f"qr drifts {drift:.3e} from lstsq"


@st.composite
def _nw_cases(draw):
    t = draw(st.integers(min_value=1, max_value=40))
    lags = draw(st.integers(min_value=0, max_value=6))
    valid_frac = draw(st.floats(min_value=0.0, max_value=1.0))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    return t, lags, valid_frac, seed


@settings(max_examples=40, deadline=None)
@given(_nw_cases(), st.sampled_from(["reference", "textbook"]))
def test_nw_mean_se_matches_transcription(case, weight):
    t, lags, valid_frac, seed = case
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(t)
    valid = rng.random(t) < valid_frac

    got = float(np.asarray(nw_mean_se(
        jnp.asarray(x), jnp.asarray(valid), lags=lags, weight=weight
    )))

    series = x[valid]  # adjacent-surviving-entry pairing (SURVEY §2.2.8)
    n = len(series)
    if n < 2:
        assert np.isnan(got)
        return
    u = series - series.mean()
    var = u @ u
    for k in range(1, lags + 1):
        if k >= n:
            break
        gamma = u[k:] @ u[:-k]
        w = max(1.0 - k / n, 0.0) if weight == "reference" else 1.0 - k / (lags + 1.0)
        var += 2.0 * w * gamma
    want = np.sqrt(var / n**2)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)
