"""Host relational transforms vs reference-semantics oracles on synthetic data."""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.synthetic import SyntheticConfig, generate_synthetic_wrds
from fm_returnprediction_tpu.panel.transform_compustat import (
    add_report_date,
    calc_book_equity,
    expand_compustat_annual_to_monthly,
    merge_CRSP_and_Compustat,
)
from fm_returnprediction_tpu.panel.transform_crsp import calculate_market_equity


@pytest.fixture(scope="module")
def wrds():
    return generate_synthetic_wrds(SyntheticConfig(n_firms=30, n_months=48))


def oracle_expand(comp_annual, id_col="gvkey"):
    """Per-gvkey groupby/reindex/ffill expansion, transcribing the reference
    (src/transform_compustat.py:101-181) loop semantics exactly."""
    df = comp_annual.drop(columns=["fyear"], errors="ignore").copy()
    df["fund_date"] = df["report_date"]
    df = df.set_index([id_col, "fund_date"]).sort_index()
    max_all = pd.to_datetime(df.index.get_level_values("fund_date")).max()
    pieces = []
    for gvkey, group in df.groupby(level=id_col):
        dates = pd.to_datetime(group.index.get_level_values("fund_date"))
        extended_max = min(max_all, dates.max() + pd.DateOffset(months=12))
        monthly = pd.date_range(dates.min(), extended_max, freq="ME")
        new_index = pd.MultiIndex.from_product(
            [[gvkey], monthly], names=[id_col, "fund_date"]
        )
        pieces.append(group.reindex(new_index, method="ffill"))
    out = pd.concat(pieces).rename_axis([id_col, "fund_date"]).reset_index()
    return out


def test_market_equity_aggregation(wrds):
    me = calculate_market_equity(wrds["crsp_m"])
    # one row per (permco, jdate)
    assert not me.duplicated(subset=["permco", "jdate"]).any()
    # firm ME equals the sum of security MEs of that permco-date
    raw = wrds["crsp_m"].dropna(subset=["prc", "shrout"]).copy()
    raw["sec_me"] = raw["prc"].abs() * raw["shrout"]
    want = raw.groupby(["permco", "jdate"])["sec_me"].sum()
    got = me.set_index(["permco", "jdate"])["me"]
    pd.testing.assert_series_equal(
        got.sort_index(), want.sort_index(), check_names=False
    )
    # the representative permno is the one with the largest security ME
    multi = raw.groupby(["permco", "jdate"]).filter(lambda g: len(g) > 1)
    if len(multi):
        top = multi.sort_values("sec_me").groupby(["permco", "jdate"]).tail(1)
        merged = top.merge(me, on=["permco", "jdate"], suffixes=("_want", ""))
        assert (merged["permno_want"] == merged["permno"]).all()


def test_report_date_four_month_lag(wrds):
    comp = add_report_date(wrds["comp"].copy())
    assert (
        comp["report_date"] == comp["datadate"] + pd.DateOffset(months=4)
    ).all()


def test_book_equity_fallback_chain():
    comp = pd.DataFrame(
        {
            "seq": [100.0, 100.0, 100.0, 100.0, 1.0],
            "txditc": [10.0, np.nan, 10.0, 10.0, np.nan],
            "pstkrv": [5.0, np.nan, np.nan, np.nan, np.nan],
            "pstkl": [7.0, 6.0, np.nan, np.nan, np.nan],
            "pstk": [8.0, 8.0, 8.0, np.nan, 50.0],
        }
    )
    out = calc_book_equity(comp.copy())
    # ps chain: pstkrv -> pstkl -> pstk -> 0; be = seq + txditc - ps, be>0 only
    np.testing.assert_allclose(out["be"].to_numpy(), [105.0, 94.0, 102.0, 110.0])
    assert len(out) == 4  # last row: be = 1 + 0 - 50 < 0 -> dropped


def test_expand_matches_reference_oracle(wrds):
    comp = calc_book_equity(add_report_date(wrds["comp"].copy()))
    got = expand_compustat_annual_to_monthly(comp)
    want = oracle_expand(comp)
    key = ["gvkey", "fund_date"]
    got_s = got.sort_values(key).reset_index(drop=True)
    want_s = want.sort_values(key).reset_index(drop=True)
    assert len(got_s) == len(want_s)
    value_cols = [c for c in want_s.columns if c not in key]
    for col in value_cols:
        a, b = got_s[col], want_s[col]
        if a.dtype.kind in "fi":
            np.testing.assert_allclose(
                a.to_numpy(dtype=float), b.to_numpy(dtype=float), err_msg=col
            )
        else:
            assert (a.fillna("") == b.fillna("")).all(), col


def test_expand_midmonth_report_dates():
    """Fiscal year ending Jun 30 -> report date Oct 30 (mid-month): the grid
    must start at Oct 31 and end at the capped month, matching date_range."""
    comp = pd.DataFrame(
        {
            "gvkey": ["1", "1"],
            "datadate": pd.to_datetime(["1980-06-30", "1981-06-30"]),
            "fyear": [1980, 1981],
            "assets": [100.0, 120.0],
        }
    )
    comp = add_report_date(comp)
    got = expand_compustat_annual_to_monthly(comp)
    want = oracle_expand(comp)
    assert list(got["fund_date"]) == list(want["fund_date"])
    np.testing.assert_allclose(got["assets"].to_numpy(), want["assets"].to_numpy())


def test_merge_link_window(wrds):
    crsp = calculate_market_equity(wrds["crsp_m"])
    comp = expand_compustat_annual_to_monthly(
        calc_book_equity(add_report_date(wrds["comp"].copy()))
    )
    merged = merge_CRSP_and_Compustat(crsp, comp, wrds["ccm"])
    assert len(merged) > 0
    # every merged row respects its link window
    ccm = wrds["ccm"].copy()
    ccm["linkenddt"] = ccm["linkenddt"].fillna(pd.Timestamp.now())
    check = merged.merge(ccm[["gvkey", "linkdt", "linkenddt"]], on="gvkey")
    assert (check["jdate"] >= check["linkdt"]).all()
    assert (check["jdate"] <= check["linkenddt"]).all()
    # fundamentals and market data coexist on each row
    assert merged[["me", "be", "assets", "retx"]].notna().all(axis=None)


def test_flag_firms_missing_variables():
    import numpy as np

    from fm_returnprediction_tpu.panel.dense import DensePanel
    from fm_returnprediction_tpu.panel.subsets import flag_firms_missing_variables

    t, n = 6, 4
    vals = np.random.default_rng(0).standard_normal((t, n, 4))
    mask = np.ones((t, n), dtype=bool)
    # firm 1: variable 2 entirely missing; firm 3: never observed at all
    vals[:, 1, 2] = np.nan
    mask[:, 3] = False
    panel = DensePanel(
        values=vals, mask=mask,
        months=np.arange("2001-01", "2001-07", dtype="datetime64[M]").astype("datetime64[ns]"),
        ids=np.array([10, 11, 12, 13]),
        var_names=["retx", "log_size", "log_bm", "return_12_2"],
    )
    assert flag_firms_missing_variables(panel) == {11}
