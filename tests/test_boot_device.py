"""Device-batched bootstrap/rolling aggregation vs the retained host
oracle (``specgrid.boot``).

The ISSUE-14 part-(b) contracts:

- the consolidated Newey-West home: ``ops.newey_west.nw_mean_se_np`` (the
  host mirror that used to live as ``engine._nw_se_np``) is differentially
  pinned against the jax kernel it mirrors, including the
  negative-variance→NaN and n<2→NaN contracts;
- the gathered device program reproduces the host per-draw loop on the
  SAME archived draw seeds (``engine.block_bootstrap_months``) at f64
  ≤ 1e-12, with exactly equal month counts;
- ``resample_matrix`` rows are byte-identical to the per-draw generator —
  the two routes never see different index rows;
- Figure-1's rolling slope means through the gathered aggregator match the
  incumbent fused-cumsum route (``ops.compaction.rolling_over_valid_rows``);
- the tile engine's device route streams the same frame as its host route
  on a bootstrapped CellSpace, and the route knob resolves with the repo's
  arg > env > default discipline.
"""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.compaction import rolling_over_valid_rows
from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth_summary
from fm_returnprediction_tpu.ops.newey_west import nw_mean_se, nw_mean_se_np
from fm_returnprediction_tpu.ops.ols import CSRegressionResult
from fm_returnprediction_tpu.specgrid.boot import (
    bootstrap_aggregate_device,
    fm_aggregate_np,
    resample_matrix,
    resolve_boot_route,
    rolling_fm_windows,
)
from fm_returnprediction_tpu.specgrid.engine import block_bootstrap_months

pytestmark = pytest.mark.specgrid


def _series(rng, t=60, p=3, nan_frac=0.1):
    slopes = rng.standard_normal((t, p))
    slopes[rng.random((t, p)) < nan_frac] = np.nan
    r2 = rng.random(t)
    n_obs = rng.integers(20, 200, t).astype(float)
    month_valid = rng.random(t) > 0.15
    return slopes, r2, n_obs, month_valid


# -- the consolidated NW home ------------------------------------------------

def test_nw_np_matches_jax_kernel():
    rng = np.random.default_rng(0)
    for n in (2, 3, 7, 30, 200):
        for lags in (0, 1, 4, 12):
            for weight in ("reference", "textbook"):
                vals = rng.standard_normal(n)
                got = nw_mean_se_np(vals, lags, weight)
                ref = float(nw_mean_se(jnp.asarray(vals),
                                       jnp.ones(n, bool),
                                       lags=lags, weight=weight))
                np.testing.assert_allclose(got, ref, rtol=1e-12,
                                           err_msg=f"n={n} lags={lags}")


def test_nw_np_contracts():
    # fewer than 2 entries → NaN (both routes)
    assert np.isnan(nw_mean_se_np(np.array([]), 4))
    assert np.isnan(nw_mean_se_np(np.array([1.0]), 4))
    # a strongly negative-autocorrelated series drives the small-sample
    # HAC variance negative: legal, reads as NaN — the same contract as
    # the jax path (guard/checks NW-tap note)
    vals = np.array([1.0, -1.0] * 5)
    assert np.isnan(nw_mean_se_np(vals, 1, "reference"))
    assert np.isnan(float(nw_mean_se(jnp.asarray(vals),
                                     jnp.ones(vals.size, bool),
                                     lags=1, weight="reference")))
    with pytest.raises(ValueError, match="weight"):
        nw_mean_se_np(np.arange(5.0), 2, "parzen")


def test_fm_aggregate_np_matches_device_summary():
    # identity gather: the host oracle and the jitted FM summary agree on
    # an unresampled series (the bootstrap parity's base case)
    rng = np.random.default_rng(1)
    slopes, r2, n_obs, month_valid = _series(rng)
    coef, tstat, nw_se, mean_r2, mean_n, n_months = fm_aggregate_np(
        slopes, r2, n_obs, month_valid, 4, 10, "reference"
    )
    cs = CSRegressionResult(
        slopes=jnp.asarray(slopes),
        intercept=jnp.zeros(slopes.shape[0]),
        r2=jnp.asarray(r2), n_obs=jnp.asarray(n_obs),
        month_valid=jnp.asarray(month_valid),
    )
    fm = fama_macbeth_summary(cs, nw_lags=4, min_months=10)
    np.testing.assert_allclose(coef, np.asarray(fm.coef), atol=1e-13)
    np.testing.assert_allclose(tstat, np.asarray(fm.tstat), atol=1e-11)
    np.testing.assert_allclose(nw_se, np.asarray(fm.nw_se), atol=1e-13)
    assert n_months == int(fm.n_months)


# -- archived draw seeds -----------------------------------------------------

def test_resample_matrix_matches_archived_per_draw_generator():
    t, draws, seed = 47, 9, 5
    mat = resample_matrix(t, draws, seed=seed)
    assert mat.shape == (draws - 1, t)
    for d in range(1, draws):
        np.testing.assert_array_equal(
            mat[d - 1], block_bootstrap_months(t, d, seed=seed)
        )
    # draw 0 is the point estimate: never resampled, never in the stack
    assert resample_matrix(t, 1, seed=seed).shape == (0, t)


@pytest.mark.parametrize("weight", ["reference", "textbook"])
def test_bootstrap_device_matches_host_oracle(weight):
    rng = np.random.default_rng(2)
    slopes, r2, n_obs, month_valid = _series(rng, t=72, p=4)
    idx = resample_matrix(72, 33, seed=7)
    coef, tstat, nw_se, mean_r2, mean_n, n_months = (
        bootstrap_aggregate_device(slopes, r2, n_obs, month_valid, idx,
                                   4, 10, weight)
    )
    assert coef.shape == (32, 4)
    for d in range(idx.shape[0]):
        rows = idx[d]
        ref = fm_aggregate_np(slopes[rows], r2[rows], n_obs[rows],
                              month_valid[rows], 4, 10, weight)
        np.testing.assert_allclose(coef[d], ref[0], atol=1e-12, err_msg=f"d={d}")
        np.testing.assert_allclose(tstat[d], ref[1], atol=1e-9, err_msg=f"d={d}")
        np.testing.assert_allclose(nw_se[d], ref[2], atol=1e-12, err_msg=f"d={d}")
        np.testing.assert_allclose(mean_r2[d], ref[3], atol=1e-12)
        np.testing.assert_allclose(mean_n[d], ref[4], atol=1e-12)
        assert int(n_months[d]) == ref[5]


# -- the rolling twin --------------------------------------------------------

def test_rolling_fm_windows_matches_fused_route():
    rng = np.random.default_rng(3)
    t, p, window, min_periods = 90, 3, 24, 12
    slopes = rng.standard_normal((t, p))
    month_valid = rng.random(t) > 0.2
    got = rolling_fm_windows(slopes, month_valid, window, min_periods)
    ref = np.asarray(rolling_over_valid_rows(
        jnp.asarray(slopes), jnp.asarray(month_valid), window, min_periods
    ))
    np.testing.assert_allclose(got, ref, atol=1e-12)
    # invalid calendar slots stay NaN in both routes
    assert np.isnan(got[~month_valid]).all()


def test_figure_rolling_slopes_device_route(monkeypatch):
    # FMRP_BOOT_ROUTE=device routes the figure's host-side rolling means
    # through the gathered aggregator; default stays the fused cumsum —
    # and the two frames agree on the pinned parity surface
    from types import SimpleNamespace

    import pandas as pd

    from fm_returnprediction_tpu.reporting.figure1 import FIGURE1_VARS
    from fm_returnprediction_tpu.reporting.figure1 import rolling_slopes

    rng = np.random.default_rng(6)
    t, p = 48, len(FIGURE1_VARS)
    cs = SimpleNamespace(
        slopes=rng.standard_normal((t, p)),
        month_valid=rng.random(t) > 0.2,
    )
    panel = SimpleNamespace(months=pd.date_range("1990-01-31", periods=t,
                                                 freq="ME"))
    monkeypatch.delenv("FMRP_BOOT_ROUTE", raising=False)
    ref = rolling_slopes(panel, None, window=12, min_periods=6, cs=cs)
    monkeypatch.setenv("FMRP_BOOT_ROUTE", "device")
    dev = rolling_slopes(panel, None, window=12, min_periods=6, cs=cs)
    pd.testing.assert_frame_equal(dev, ref, atol=1e-12, rtol=0,
                                  check_exact=False)


def test_rolling_fm_windows_empty_series():
    out = rolling_fm_windows(np.zeros((5, 2)), np.zeros(5, bool), 3, 1)
    assert np.isnan(out).all()


# -- route knob --------------------------------------------------------------

def test_boot_route_resolution(monkeypatch):
    monkeypatch.delenv("FMRP_BOOT_ROUTE", raising=False)
    assert resolve_boot_route() == "auto"
    monkeypatch.setenv("FMRP_BOOT_ROUTE", "host")
    assert resolve_boot_route() == "host"
    assert resolve_boot_route("device") == "device"  # arg beats env
    monkeypatch.setenv("FMRP_BOOT_ROUTE", "gpu")
    with pytest.raises(ValueError, match="boot route"):
        resolve_boot_route()


# -- the tile engine's two routes -------------------------------------------

def test_engine_device_route_matches_host_route():
    from fm_returnprediction_tpu.specgrid import CellSpace, run_cellspace

    rng = np.random.default_rng(4)
    t, n, p = 36, 120, 4
    x = rng.standard_normal((t, n, p))
    x[rng.random(x.shape) < 0.05] = np.nan
    y = rng.standard_normal((t, n))
    y[rng.random(y.shape) < 0.1] = np.nan
    masks = {"All": np.ones((t, n), bool)}
    names = tuple(f"x{i}" for i in range(p))
    space = CellSpace(
        regressor_sets=(("m2", names[:2]), ("m4", names)),
        universes=("All",),
        windows=(("full", None), ("late", (18, 36))),
        bootstrap=6,
    )
    frames = {}
    for route in ("host", "device"):
        frame, stats = run_cellspace(
            y, x, masks, space, mask=masks["All"], seed=11,
            boot_route=route,
        )
        assert stats["boot_route"] == route
        frames[route] = frame.sort_values(
            ["cell", "predictor"]
        ).reset_index(drop=True)
    h, d = frames["host"], frames["device"]
    assert len(h) == len(d)
    for col in ("cell", "model", "universe", "window", "predictor",
                "draw", "n_months"):
        assert (h[col] == d[col]).all(), col
    for col in ("coef", "tstat", "nw_se", "mean_r2", "mean_n"):
        pd.testing.assert_series_equal(h[col], d[col], atol=1e-9,
                                       rtol=1e-9, check_exact=False)
