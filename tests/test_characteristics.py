"""Characteristic engine vs the pandas/reference-formula oracles, end to end
on synthetic WRDS-shaped data."""

import numpy as np
import pandas as pd
import pytest

from oracle import (
    oracle_monthly_characteristics,
    oracle_std_12,
    oracle_weekly_beta,
    oracle_winsorize,
)

from fm_returnprediction_tpu.data.synthetic import SyntheticConfig, generate_synthetic_wrds
from fm_returnprediction_tpu.panel.characteristics import FACTORS_DICT, get_factors
from fm_returnprediction_tpu.panel.dense import dense_to_long
from fm_returnprediction_tpu.panel.transform_compustat import (
    add_report_date,
    calc_book_equity,
    expand_compustat_annual_to_monthly,
    merge_CRSP_and_Compustat,
)
from fm_returnprediction_tpu.panel.transform_crsp import calculate_market_equity


@pytest.fixture(scope="module")
def pipeline():
    wrds = generate_synthetic_wrds(SyntheticConfig(n_firms=25, n_months=48))
    crsp = calculate_market_equity(wrds["crsp_m"])
    comp = expand_compustat_annual_to_monthly(
        calc_book_equity(add_report_date(wrds["comp"].copy()))
    )
    merged = merge_CRSP_and_Compustat(crsp, comp, wrds["ccm"])
    merged["mthcaldt"] = merged["jdate"]  # synthetic monthly dates are month-ends
    panel, factors = get_factors(merged, wrds["crsp_d"], wrds["crsp_index_d"])
    return wrds, merged, panel, factors


@pytest.fixture(scope="module")
def oracle_panel(pipeline):
    wrds, merged, _, _ = pipeline
    df = oracle_monthly_characteristics(merged)
    df = oracle_std_12(wrds["crsp_d"], df)
    df = oracle_weekly_beta(wrds["crsp_d"], wrds["crsp_index_d"], df)
    df = oracle_winsorize(df, list(FACTORS_DICT.values()))
    return df


def _dense_as_long(panel):
    out = dense_to_long(panel).rename(columns={"date": "jdate", "id": "permno"})
    return out.set_index(["permno", "jdate"]).sort_index()


@pytest.mark.parametrize("var", list(FACTORS_DICT.values()))
def test_characteristic_matches_oracle(pipeline, oracle_panel, var):
    _, _, panel, _ = pipeline
    got = _dense_as_long(panel)[var]
    want = oracle_panel.set_index(["permno", "jdate"]).sort_index()[var]
    assert got.index.equals(want.index)
    g, w = got.to_numpy(), want.to_numpy()
    both_nan = np.isnan(g) & np.isnan(w)
    np.testing.assert_allclose(
        np.where(both_nan, 0.0, g),
        np.where(both_nan, 0.0, w),
        rtol=1e-7,
        atol=1e-10,
        err_msg=var,
    )


def test_beta_recovers_true_loading(pipeline):
    """Synthetic daily returns are beta_true * mkt + noise: the estimated
    betas should correlate strongly with plausible magnitudes."""
    _, _, panel, _ = pipeline
    beta = panel.var("beta")
    finite = np.isfinite(beta)
    assert finite.sum() > 50
    vals = beta[finite]
    assert 0.0 < np.median(vals) < 2.5


def test_all_factor_columns_present(pipeline):
    _, _, panel, factors = pipeline
    for col in factors.values():
        assert col in panel.var_names, col
