"""Opt-in Turnover_{-1,-12} characteristic (INCLUDE_TURNOVER=1).

The published Lewellen Table 1 has a Turnover row the reference pipeline
never computes (no calc function, volume never pulled — SURVEY §6 note).
This framework computes it from monthly volume: turnover_m = vol_m /
(shrout_m · 1000), averaged over the 12 rows ending at t-1, all 12 required.
Oracle: an independent per-firm pandas transcription of exactly that
definition (groupby shift + rolling mean over each firm's consecutive
rows, the same row-based semantics as the other monthly characteristics).
"""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.synthetic import (
    SyntheticConfig,
    generate_synthetic_wrds,
)
from fm_returnprediction_tpu.panel.characteristics import (
    TURNOVER_COLUMN,
    TURNOVER_LABEL,
)
from fm_returnprediction_tpu.pipeline import build_panel


@pytest.fixture(scope="module")
def built():
    data = generate_synthetic_wrds(SyntheticConfig(n_firms=40, n_months=48))
    panel, factors = build_panel(data, include_turnover=True)
    return data, panel, factors


def _oracle_turnover(crsp_m: pd.DataFrame) -> pd.DataFrame:
    """Reference-formula transcription on the raw monthly frame."""
    df = crsp_m.sort_values(["permno", "mthcaldt"]).copy()
    df["turn"] = df["vol"] / (df["shrout"] * 1000.0)
    df["turnover_12"] = df.groupby("permno")["turn"].transform(
        lambda s: s.shift(1).rolling(12, min_periods=12).mean()
    )
    return df


def test_turnover_matches_pandas_oracle(built):
    data, panel, factors = built
    assert factors[TURNOVER_LABEL] == TURNOVER_COLUMN
    got = np.asarray(panel.var(TURNOVER_COLUMN))

    # The panel keeps one representative permno per (permco, month) (ME
    # dedup), so compare only rows present in the dense panel.
    oracle = _oracle_turnover(data["crsp_m"])
    months = pd.DatetimeIndex(panel.months)
    ids = panel.ids
    t_index = {m: i for i, m in enumerate(months)}
    n_index = {p: i for i, p in enumerate(ids)}

    checked = 0
    mask = np.asarray(panel.mask)
    for row in oracle.itertuples():
        ti = t_index.get(row.mthcaldt)
        ni = n_index.get(row.permno)
        if ti is None or ni is None or not mask[ti, ni]:
            continue
        want = row.turnover_12
        have = got[ti, ni]
        if np.isnan(want):
            assert np.isnan(have), (row.permno, row.mthcaldt, have)
        else:
            # winsorize clips the cross-sectional tails — values inside the
            # clip bounds must match exactly; clipped ones must not exceed
            # the unclipped oracle magnitude ordering. Check unclipped rows
            # by tolerance and count them.
            if np.isfinite(have) and abs(have - want) < 1e-9:
                checked += 1
    assert checked > 200, f"only {checked} turnover cells matched unclipped"


def test_turnover_absent_by_default(built):
    data, _, _ = built
    panel, factors = build_panel(data, include_turnover=False)
    assert TURNOVER_LABEL not in factors
    assert TURNOVER_COLUMN not in panel.var_names


def test_turnover_requires_volume_column(built):
    data, _, _ = built
    slim = dict(data)
    slim["crsp_m"] = data["crsp_m"].drop(columns=["vol"])
    with pytest.raises(KeyError, match="vol"):
        build_panel(slim, include_turnover=True)


def test_turnover_row_reaches_table_1(built):
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.reporting.table1 import build_table_1

    _, panel, factors = built
    masks = compute_subset_masks(panel)
    t1 = build_table_1(panel, masks, factors)
    assert TURNOVER_LABEL in t1.index
    avg = float(t1.loc[TURNOVER_LABEL, ("All stocks", "Avg")])
    assert np.isfinite(avg) and 0.0 < avg < 1.0
