"""Opt-in Turnover_{-1,-12} characteristic (INCLUDE_TURNOVER=1).

The published Lewellen Table 1 has a Turnover row the reference pipeline
never computes (no calc function, volume never pulled — SURVEY §6 note).
This framework computes it from monthly volume: turnover_m = vol_m /
(shrout_m · 1000), averaged over the 12 rows ending at t-1, all 12 required.
Oracle: an independent per-firm pandas transcription of exactly that
definition (groupby shift + rolling mean over each firm's consecutive
rows, the same row-based semantics as the other monthly characteristics).
"""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.synthetic import (
    SyntheticConfig,
    generate_synthetic_wrds,
)
from fm_returnprediction_tpu.panel.characteristics import (
    TURNOVER_COLUMN,
    TURNOVER_LABEL,
)
from fm_returnprediction_tpu.pipeline import build_panel


@pytest.fixture(scope="module")
def built():
    data = generate_synthetic_wrds(SyntheticConfig(n_firms=40, n_months=48))
    panel, factors = build_panel(data, include_turnover=True)
    return data, panel, factors


def _oracle_turnover_panel(crsp_m: pd.DataFrame, panel) -> np.ndarray:
    """Pandas transcription of the panel's turnover on the panel's own rows.

    The characteristic rolls over COMPACTED rows — the sequence of months a
    firm is actually present in the panel (pandas row semantics, same as
    every other monthly characteristic) — so the oracle scatters the raw
    monthly turnover into the (T, N) panel layout and rolls each firm's
    present-row sequence with an independent pandas shift+rolling."""
    mask = np.asarray(panel.mask)
    months = pd.DatetimeIndex(panel.months)
    t_index = {m: i for i, m in enumerate(months)}
    n_index = {p: i for i, p in enumerate(panel.ids)}

    turn = np.full(mask.shape, np.nan)
    df = crsp_m.copy()
    df["turn"] = df["vol"] / (df["shrout"] * 1000.0)
    for row in df.itertuples():
        ti = t_index.get(row.mthcaldt)
        ni = n_index.get(row.permno)
        if ti is not None and ni is not None and mask[ti, ni]:
            turn[ti, ni] = row.turn

    out = np.full(mask.shape, np.nan)
    for ni in range(mask.shape[1]):
        rows = np.flatnonzero(mask[:, ni])
        if rows.size == 0:
            continue
        rolled = (
            pd.Series(turn[rows, ni]).shift(1).rolling(12, min_periods=12).mean()
        )
        out[rows, ni] = rolled.to_numpy()
    return out


def test_turnover_matches_pandas_oracle(built):
    """Every panel cell is asserted: the oracle reproduces the raw rolling
    turnover AND the per-month [1%, 99%] winsorization (min 5 obs,
    ``ops.quantiles.winsorize_cs`` semantics), so in-bounds cells must agree
    to 1e-9 and clipped cells must land exactly on the computed bound — a
    systematic error anywhere can no longer hide behind a match count."""
    data, panel, factors = built
    assert factors[TURNOVER_LABEL] == TURNOVER_COLUMN
    got = np.asarray(panel.var(TURNOVER_COLUMN))
    mask = np.asarray(panel.mask)

    want_raw = _oracle_turnover_panel(data["crsp_m"], panel)

    # Reproduce the pipeline's winsorization in the oracle: per month,
    # 1st/99th percentile (linear interpolation) over the finite masked
    # cross-section, clip when >= 5 observations, passthrough otherwise.
    want = want_raw.copy()
    n_clipped = 0
    for ti in range(got.shape[0]):
        ok = mask[ti] & np.isfinite(want_raw[ti])
        if ok.sum() < 5:
            continue
        lo, hi = np.percentile(want_raw[ti][ok], [1.0, 99.0])
        clipped = np.clip(want_raw[ti], lo, hi)
        n_clipped += int((clipped[ok] != want_raw[ti][ok]).sum())
        want[ti] = np.where(ok, clipped, want_raw[ti])

    in_panel = mask & np.isfinite(want)
    assert in_panel.sum() > 200  # the fixture must exercise a real panel
    np.testing.assert_allclose(
        got[in_panel], want[in_panel], rtol=0, atol=1e-9
    )
    # NaN cells (warm-up months, gaps) must be NaN in the panel too.
    nan_cells = mask & np.isnan(want)
    assert np.isnan(got[nan_cells]).all()
    # The bound-clamping branch must actually have been exercised.
    assert n_clipped > 0, "fixture never clipped a cell; winsorize untested"


def test_turnover_absent_by_default(built):
    data, _, _ = built
    panel, factors = build_panel(data, include_turnover=False)
    assert TURNOVER_LABEL not in factors
    assert TURNOVER_COLUMN not in panel.var_names


def test_turnover_requires_volume_column(built):
    data, _, _ = built
    slim = dict(data)
    slim["crsp_m"] = data["crsp_m"].drop(columns=["vol"])
    with pytest.raises(KeyError, match="vol"):
        build_panel(slim, include_turnover=True)


def test_turnover_row_reaches_table_1(built):
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.reporting.table1 import build_table_1

    _, panel, factors = built
    masks = compute_subset_masks(panel)
    t1 = build_table_1(panel, masks, factors)
    assert TURNOVER_LABEL in t1.index
    avg = float(t1.loc[TURNOVER_LABEL, ("All stocks", "Avg")])
    assert np.isfinite(avg) and 0.0 < avg < 1.0
