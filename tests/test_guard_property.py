"""Property tests: guards are semantically FREE on clean data.

The acceptance contract for the guardrail layer is that it can stay on in
production: a clean end-to-end run must produce bit-identical artifacts,
pay zero extra traces/compiles on the hot paths, and the guard-off
configuration must contain literally no guard code. Each property is
pinned here:

- OFF IS A NO-OP: with guards off, tracing the hot paths never touches the
  sentinel helpers at all (proved by replacing them with bombs), and the
  off-jaxpr has strictly fewer equations than the on-jaxpr (the sentinels
  only ever ADD).
- ON IS INVISIBLE IN THE NUMBERS: monthly OLS, Fama-MacBeth, the spec-grid
  program and the whole synthetic pipeline return bit-identical results
  guarded vs unguarded.
- ON COSTS ZERO EXTRA TRACES: per configuration, the OLS/Gram programs
  trace exactly once whether guards are armed or not (counted by the same
  trace-side-effect counters the specgrid bench uses).
"""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.guard import checks

pytestmark = pytest.mark.guard


@pytest.fixture(autouse=True)
def _clean_counters():
    checks.reset()
    yield
    checks.reset()


def _data(t=10, n=24, p=3, seed=7, dtype=np.float64):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(dtype)
    beta = (rng.standard_normal(p) * 0.05).astype(dtype)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(dtype)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(dtype)
    return y, x, mask


def _tree_equal(a, b):
    import jax

    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_monthly_cs_ols_bit_identical_and_off_is_pristine(monkeypatch):
    from fm_returnprediction_tpu.ops import ols

    y, x, mask = _data()
    with checks.guards(False):
        off = ols.monthly_cs_ols(y, x, mask)
    with checks.guards(True):
        on = ols.monthly_cs_ols(y, x, mask)
    _tree_equal(off, on)
    assert checks.counters() == {}  # clean data: nothing to report

    # guard-off tracing must never reach the sentinel helpers: make them
    # explode and trace anyway — only the guarded trace may blow up
    import jax

    def boom(*a, **k):  # pragma: no cover - must not run on the off path
        raise AssertionError("guard helper executed with guards off")

    monkeypatch.setattr(checks, "cs_counters", boom)
    monkeypatch.setattr(checks, "nonfinite_count", boom)
    monkeypatch.setattr(checks, "cond_limit", boom)
    ols._monthly_cs_ols.clear_cache()  # force genuine retraces
    jax.make_jaxpr(
        lambda *a: ols._monthly_cs_ols(*a, solver="qr", guard=False)
    )(y, x, mask)  # traces clean: no guard code on the off path
    with pytest.raises(AssertionError, match="guards off"):
        jax.make_jaxpr(
            lambda *a: ols._monthly_cs_ols(*a, solver="qr", guard=True)
        )(y, x, mask)


def test_guard_on_jaxpr_is_off_jaxpr_plus_counters():
    import jax

    from fm_returnprediction_tpu.ops import ols

    y, x, mask = _data()
    jx_off = jax.make_jaxpr(
        lambda *a: ols._monthly_cs_ols(*a, solver="qr", guard=False)
    )(y, x, mask)
    jx_on = jax.make_jaxpr(
        lambda *a: ols._monthly_cs_ols(*a, solver="qr", guard=True)
    )(y, x, mask)

    def inner_eqns(jx):
        # tracing through the jit boundary leaves one pjit eqn wrapping
        # the real program — compare the wrapped jaxprs
        (eqn,) = jx.jaxpr.eqns
        return eqn.params["jaxpr"].jaxpr.eqns

    # sentinels only ADD equations/outputs; the result leaves are the same
    assert len(inner_eqns(jx_on)) > len(inner_eqns(jx_off))
    assert jx_on.out_avals[: len(jx_off.out_avals)] == list(jx_off.out_avals)


def test_fama_macbeth_bit_identical_and_zero_extra_traces():
    from fm_returnprediction_tpu.ops import ols
    from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth

    y, x, mask = _data(seed=11)
    fama_macbeth.clear_cache()
    ols._monthly_cs_ols.clear_cache()
    ols.TRACES.clear()
    with checks.guards(False):
        off = fama_macbeth(y, x, mask)
        off2 = fama_macbeth(y, x, mask)
    traces_off = dict(ols.TRACES)
    with checks.guards(True):
        on = fama_macbeth(y, x, mask)
        on2 = fama_macbeth(y, x, mask)
    traces_on = {
        k: v - traces_off.get(k, 0) for k, v in ols.TRACES.items()
    }
    _tree_equal(off, on)
    _tree_equal(off2, on2)
    # one trace per configuration, repeat calls hit the cache either way —
    # arming guards costs zero EXTRA traces
    assert traces_off == {"monthly_cs_ols": 1}
    assert traces_on == {"monthly_cs_ols": 1}


def test_specgrid_program_bit_identical_one_trace_each():
    from fm_returnprediction_tpu.specgrid import run_spec_grid
    from fm_returnprediction_tpu.specgrid.solve import PROGRAM_TRACES
    from fm_returnprediction_tpu.specgrid.specs import Spec, SpecGrid

    rng = np.random.default_rng(13)
    t, n = 24, 40
    preds = ("a", "b", "c")
    x = rng.standard_normal((t, n, len(preds)))
    y = 0.05 * rng.standard_normal((t, n))
    masks = {"All stocks": rng.random((t, n)) > 0.1}
    grid = SpecGrid((
        Spec("all", preds, "All stocks"),
        Spec("pair", preds[:2], "All stocks"),
    ), min_months=4)

    before = dict(PROGRAM_TRACES)
    with checks.guards(False):
        off = run_spec_grid(y, x, masks, grid)
    mid = dict(PROGRAM_TRACES)
    with checks.guards(True):
        on = run_spec_grid(y, x, masks, grid)
    after = dict(PROGRAM_TRACES)
    for la, lb in zip(off[:-1], on[:-1]):  # leaves before referee_specs
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert off.referee_specs == on.referee_specs
    assert mid["specgrid_program"] - before.get("specgrid_program", 0) == 1
    assert after["specgrid_program"] - mid["specgrid_program"] == 1


def test_pipeline_bit_identical_artifacts_guard_on_vs_off():
    """The whole synthetic pipeline: guarded and unguarded runs emit
    bit-identical tables, deciles and serving state, and the guarded
    clean run's audit carries no violations and no quarantines."""
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.pipeline import run_pipeline

    kw = dict(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=24, n_months=42),
        make_figure=False, make_deciles=True, make_serving=True,
        compile_pdf=False,
    )
    on = run_pipeline(**kw, guard=True)
    off = run_pipeline(**kw, guard=False)
    pd.testing.assert_frame_equal(on.table_1, off.table_1)
    pd.testing.assert_frame_equal(on.table_2, off.table_2)
    pd.testing.assert_frame_equal(on.decile_table, off.decile_table)
    np.testing.assert_array_equal(
        on.serving_state.coef, off.serving_state.coef
    )
    np.testing.assert_array_equal(
        on.serving_state.slopes_bar, off.serving_state.slopes_bar
    )
    assert on.audit.violations == []
    assert on.audit.quarantined == []


def test_guard_flag_resolution_and_context():
    assert checks.guard_active() in (True, False)
    prev = checks.guard_active()
    with checks.guards(not prev):
        assert checks.guard_active() is (not prev)
    assert checks.guard_active() is prev
