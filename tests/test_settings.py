"""L0 config tests (reference semantics: ``src/settings.py:72-94``)."""

import pandas as pd
import pytest

from fm_returnprediction_tpu import settings
from fm_returnprediction_tpu.settings import config, read_env_file


def test_default_sample_period():
    assert config("START_DATE") == pd.Timestamp("1964-01-01")
    assert config("END_DATE") == pd.Timestamp("2013-12-31")


def test_directory_layout():
    data_dir = config("DATA_DIR")
    assert config("RAW_DATA_DIR") == data_dir / "raw"
    assert config("PROCESSED_DATA_DIR") == data_dir / "processed"
    assert config("MANUAL_DATA_DIR") == data_dir / "manual"


def test_backend_key_exists():
    assert config("BACKEND") in {"cpu", "tpu"}


def test_double_default_guard():
    with pytest.raises(ValueError):
        config("START_DATE", default="1999-01-01")


def test_type_change_guard():
    with pytest.raises(ValueError):
        config("START_DATE", cast=str)


def test_unknown_key_raises():
    with pytest.raises(KeyError):
        config("NO_SUCH_KEY_EVER")


def test_unknown_key_with_default():
    assert config("NO_SUCH_KEY_EVER", default="fallback") == "fallback"


def test_read_env_file(tmp_path):
    env = tmp_path / ".env"
    env.write_text("# comment\nFOO=bar\nQUOTED='baz'\n\nBAD_LINE\n")
    values = read_env_file(env)
    assert values == {"FOO": "bar", "QUOTED": "baz"}


def test_create_dirs(tmp_path, monkeypatch):
    for key in ("DATA_DIR", "RAW_DATA_DIR", "PROCESSED_DATA_DIR",
                "MANUAL_DATA_DIR", "OUTPUT_DIR"):
        monkeypatch.setitem(settings.d, key, tmp_path / key.lower())
    settings.create_dirs()
    assert (tmp_path / "raw_data_dir").is_dir()


def test_apply_backend_cpu_and_validation(monkeypatch):
    import os

    from fm_returnprediction_tpu.settings import apply_backend

    monkeypatch.setenv("JAX_PLATFORMS", "")
    assert apply_backend("cpu") == "cpu"
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    assert apply_backend("tpu") == "tpu"  # leaves resolution to JAX
    import pytest

    with pytest.raises(ValueError, match="BACKEND"):
        apply_backend("cuda")
