"""Bench-scale generator: schema parity with the hermetic synthetic backend
and pipeline runnability — the bench must exercise the same code paths the
tests verify, or its numbers describe a different program."""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.benchscale import (
    generate_benchscale_wrds,
    write_benchscale_cache,
)
from fm_returnprediction_tpu.data.synthetic import SyntheticConfig, generate_synthetic_wrds


@pytest.fixture(scope="module")
def both():
    bench = generate_benchscale_wrds(n_permnos=120, n_months=48)
    synth = generate_synthetic_wrds(SyntheticConfig(n_firms=30, n_months=24))
    return bench, synth


def test_benchscale_schema_covers_synthetic(both):
    """Every column the hermetic generator emits (and therefore every column
    the pipeline may touch) exists in the bench-scale frames with a
    compatible kind — except jdate, which the pipeline derives when absent."""
    bench, synth = both
    derivable = {"crsp_m": set(), "crsp_d": set(), "comp": set(), "ccm": set(),
                 "crsp_index_d": set()}
    for key in synth:
        missing = set(synth[key].columns) - set(bench[key].columns) - derivable[key]
        assert not missing, f"{key} missing columns: {missing}"


def test_benchscale_pipeline_runs_and_recovers_beta(tmp_path):
    from fm_returnprediction_tpu.pipeline import run_pipeline

    write_benchscale_cache(tmp_path, n_permnos=100, n_months=48)
    res = run_pipeline(raw_data_dir=tmp_path, make_figure=False,
                       make_deciles=False, compile_pdf=False, output_dir=None)
    beta = res.panel.var("beta")
    finite = np.isfinite(beta)
    assert finite.sum() > 200
    # betas were drawn U(0.3, 1.8); the factor loadings must be recoverable
    assert 0.6 < float(np.nanmean(beta)) < 1.5
    assert isinstance(res.table_2, pd.DataFrame) and len(res.table_2) > 0


def test_benchscale_cache_reuse(tmp_path):
    p1 = write_benchscale_cache(tmp_path, n_permnos=40, n_months=30)
    marker = (tmp_path / "benchscale.json").read_text()
    mtime = (tmp_path / "CRSP_stock_d.parquet").stat().st_mtime_ns
    p2 = write_benchscale_cache(tmp_path, n_permnos=40, n_months=30)
    assert p1 == p2
    assert (tmp_path / "CRSP_stock_d.parquet").stat().st_mtime_ns == mtime
    # changed params regenerate
    write_benchscale_cache(tmp_path, n_permnos=41, n_months=30)
    assert (tmp_path / "benchscale.json").read_text() != marker
