"""``utils.timing``: stage accounting and the async-attribution barrier.

The round-4 driver artifact charged Table 1 47 s at real shape because
JAX's async dispatch let upstream panel work drain inside Table 1's first
``device_get`` — stage walls measured who BLOCKED, not who COMPUTED.
``stage_sync`` is the fix; these tests pin its contract: a no-op by
default (production keeps cross-stage overlap), a real
``block_until_ready`` barrier under ``FMRP_SYNC_STAGES=1``.
"""

import jax.numpy as jnp
import pytest

from fm_returnprediction_tpu.utils.timing import StageTimer, stage_sync


def test_stage_sync_default_noop(monkeypatch):
    monkeypatch.delenv("FMRP_SYNC_STAGES", raising=False)
    called = []
    monkeypatch.setattr("jax.block_until_ready",
                        lambda v: called.append(v) or v)
    stage_sync(jnp.ones(3))
    assert called == []


def test_stage_sync_blocks_when_enabled(monkeypatch):
    monkeypatch.setenv("FMRP_SYNC_STAGES", "1")
    called = []
    monkeypatch.setattr("jax.block_until_ready",
                        lambda v: called.append(v) or v)
    # pytree values (a stage's dict of masks) pass through whole
    tree = {"a": jnp.ones(2), "b": jnp.zeros(2)}
    stage_sync(tree)
    assert called == [tree]


def test_stage_timer_nested_total():
    timer = StageTimer()
    with timer.stage("parent"):
        with timer.stage("parent/child"):
            pass
    # "/"-names are nested sub-stages: counted in durations, excluded
    # from total() so the parent's wall is not double-counted
    assert "parent/child" in timer.durations
    assert timer.total() == timer.durations["parent"]


def test_stage_timer_orphan_nested_name_rejected_by_total():
    # a "/"-named stage recorded with NO parent stage open: its seconds
    # are in no top-level stage, so total() would silently drop them —
    # the convention is validated, not just documented
    timer = StageTimer()
    with timer.stage("loose/child"):
        pass
    assert "loose/child" in timer.durations  # still recorded
    with pytest.raises(ValueError, match="no parent stage open"):
        timer.total()


def test_stage_timer_shadowed_top_level_name_rejected_by_total():
    # the dual failure: a top-level (no "/") name opened INSIDE another
    # stage would be counted twice by total()
    timer = StageTimer()
    with timer.stage("outer"):
        with timer.stage("inner_top_level"):
            pass
    with pytest.raises(ValueError, match="counted twice"):
        timer.total()


def test_stage_timer_ensure_stage_covers_standalone_helpers():
    # ensure_stage: a real stage when nothing is open (standalone helper
    # call), a no-op when the caller already opened one
    timer = StageTimer()
    with timer.ensure_stage("build_panel"):
        with timer.stage("panel/sub"):
            pass
    assert timer.total() == timer.durations["build_panel"]

    timer2 = StageTimer()
    with timer2.stage("caller"):
        with timer2.ensure_stage("build_panel"):  # no-op: caller is open
            with timer2.stage("panel/sub"):
                pass
    assert "build_panel" not in timer2.durations
    assert timer2.total() == timer2.durations["caller"]


def test_stage_timer_mark_skipped_records_reason_not_zero():
    # a deliberately-skipped stage must be distinguishable from one that
    # ran in ~0 s: no durations entry, an explicit reason, and visibility
    # in the report
    timer = StageTimer()
    timer.mark_skipped("load_raw_data", "prepared checkpoint hit")
    assert "load_raw_data" not in timer.durations
    assert timer.skipped == {"load_raw_data": "prepared checkpoint hit"}
    assert "skipped (prepared checkpoint hit)" in timer.report()
    assert timer.total() == 0.0

    # a stage that later actually runs clears its skip marker
    with timer.stage("load_raw_data"):
        pass
    assert "load_raw_data" in timer.durations
    assert "load_raw_data" not in timer.skipped
