"""``utils.timing``: stage accounting and the async-attribution barrier.

The round-4 driver artifact charged Table 1 47 s at real shape because
JAX's async dispatch let upstream panel work drain inside Table 1's first
``device_get`` — stage walls measured who BLOCKED, not who COMPUTED.
``stage_sync`` is the fix; these tests pin its contract: a no-op by
default (production keeps cross-stage overlap), a real
``block_until_ready`` barrier under ``FMRP_SYNC_STAGES=1``.
"""

import jax.numpy as jnp

from fm_returnprediction_tpu.utils.timing import StageTimer, stage_sync


def test_stage_sync_default_noop(monkeypatch):
    monkeypatch.delenv("FMRP_SYNC_STAGES", raising=False)
    called = []
    monkeypatch.setattr("jax.block_until_ready",
                        lambda v: called.append(v) or v)
    stage_sync(jnp.ones(3))
    assert called == []


def test_stage_sync_blocks_when_enabled(monkeypatch):
    monkeypatch.setenv("FMRP_SYNC_STAGES", "1")
    called = []
    monkeypatch.setattr("jax.block_until_ready",
                        lambda v: called.append(v) or v)
    # pytree values (a stage's dict of masks) pass through whole
    tree = {"a": jnp.ones(2), "b": jnp.zeros(2)}
    stage_sync(tree)
    assert called == [tree]


def test_stage_timer_nested_total():
    timer = StageTimer()
    with timer.stage("parent"):
        with timer.stage("parent/child"):
            pass
    # "/"-names are nested sub-stages: counted in durations, excluded
    # from total() so the parent's wall is not double-counted
    assert "parent/child" in timer.durations
    assert timer.total() == timer.durations["parent"]
