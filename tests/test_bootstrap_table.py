"""Bootstrap SE reporting artifact (BASELINE configs[4] as a table)."""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.data.synthetic import (
    SyntheticConfig,
    generate_synthetic_wrds,
)
from fm_returnprediction_tpu.models.lewellen import MODELS
from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
from fm_returnprediction_tpu.pipeline import build_panel, run_pipeline
from fm_returnprediction_tpu.reporting.bootstrap_table import (
    build_bootstrap_table,
)


@pytest.fixture(scope="module")
def built():
    data = generate_synthetic_wrds(SyntheticConfig(n_firms=50, n_months=60))
    panel, factors = build_panel(data)
    return panel, factors, compute_subset_masks(panel)


def test_bootstrap_table_layout_and_determinism(built):
    panel, factors, masks = built
    t1 = build_bootstrap_table(panel, masks, factors, n_replicates=64, seed=3)
    t2 = build_bootstrap_table(panel, masks, factors, n_replicates=64, seed=3)
    pd.testing.assert_frame_equal(t1, t2)  # key-deterministic

    n_rows = sum(len(m.predictors) for m in MODELS)
    assert t1.shape == (n_rows, 3 * 4)
    stats = [c[1] for c in t1.columns[:4]]
    assert stats == ["Slope", "Boot SE", "t (boot)", "t (NW)"]

    # Model-1 cells (few predictors, well-populated months) must be finite,
    # with positive SEs and boot-t on the same scale as NW-t.
    m1 = t1.loc[MODELS[0].name]
    sub = t1.columns.levels[0][0]
    assert np.isfinite(m1[(sub, "Slope")].astype(float)).all()
    assert (m1[(sub, "Boot SE")].astype(float) > 0).all()
    ratio = (
        m1[(sub, "t (boot)")].astype(float).abs()
        / m1[(sub, "t (NW)")].astype(float).abs()
    )
    assert ((ratio > 0.2) & (ratio < 5.0)).all(), ratio.to_list()


def test_bootstrap_table_through_pipeline(tmp_path):
    res = run_pipeline(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=40, n_months=48),
        make_figure=False, make_deciles=False, compile_pdf=False,
        make_bootstrap=True, bootstrap_replicates=32,
        output_dir=tmp_path,
    )
    assert res.bootstrap_table is not None
    assert "bootstrap_table" in res.timer.durations
    assert (tmp_path / "bootstrap_se.pkl").exists()
    assert (tmp_path / "bootstrap_se.tex").exists()
