"""Independent calendar-semantics oracle for the weekly rolling beta.

VERDICT r2 item 6: the polars differential test skips when polars is not
installable, leaving kernel and ``tests/oracle.py`` sharing one author's
reading of ``group_by_dynamic`` (``src/calc_Lewellen_2014.py:396-430``).
This file is a SECOND, from-scratch implementation of the reference's
weekly-window contract that shares no code or representation with either:
plain ``datetime.date`` arithmetic, explicit ``[monday, monday + 156w)``
row scans per firm, dict-of-rows data model. It asserts, on adversarial
calendars, the full contract:

- window starts anchor on the GLOBAL Monday lattice (``truncate("1w")``),
  including weeks whose Monday has no trading row (holiday Mondays);
- windows are label-left and FORWARD: rows with ``monday <= d < monday+156w``;
- per firm, starts run from its first to its last observed week, and a
  start is emitted only when its window contains >= 1 joined row;
- the inner stock x index join drops firm rows on days the index lacks;
- null returns occupy window rows (the denominator ``n`` counts ALL rows)
  but are excluded from the partial sums; null market values are excluded
  from Σrm/Σrm² and windows with no market row give null beta;
- degenerate windows (n < 2) give null beta;
- each start is stamped with the month of its MONDAY (year-boundary weeks
  stamp December, not January) and deduplicated keep-LAST per firm-month.
"""

import math
from collections import defaultdict
from datetime import date, timedelta

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

WINDOW_WEEKS = 156


def _monday(d: date) -> date:
    return d - timedelta(days=d.weekday())


def _is_null(v) -> bool:
    return v is None or (isinstance(v, float) and math.isnan(v))


def oracle_weekly_betas(stock_rows, index_rows, window_weeks=WINDOW_WEEKS):
    """From-scratch transcription of the reference's weekly beta contract.

    stock_rows : iterable of (permno, date, retx_or_None)
    index_rows : mapping date -> vwretx_or_None (row presence = key presence)
    Returns {(permno, (year, month)): beta_or_None} after keep-last dedup.
    """
    joined = defaultdict(list)
    for p, d, r in stock_rows:
        if d in index_rows:  # inner join: firm rows without an index row drop
            joined[p].append((d, r, index_rows[d]))

    out = {}
    for p, rows in joined.items():
        rows.sort(key=lambda t: t[0])
        w = _monday(rows[0][0])
        last_w = _monday(rows[-1][0])
        while w <= last_w:
            lo, hi = w, w + timedelta(weeks=window_weeks)
            win = [(r, m) for (d, r, m) in rows if lo <= d < hi]
            n = len(win)
            if n >= 1:
                ri = [math.log1p(r) for r, m in win if not _is_null(r)]
                rm = [math.log1p(m) for r, m in win if not _is_null(m)]
                both = [
                    math.log1p(r) * math.log1p(m)
                    for r, m in win
                    if not _is_null(r) and not _is_null(m)
                ]
                if n >= 2 and len(rm) >= 1:
                    cov = sum(both) - sum(ri) * sum(rm) / n
                    var = sum(v * v for v in rm) - sum(rm) * sum(rm) / n
                    beta = cov / var if var != 0.0 else None
                else:
                    beta = None
                # keep-last: ascending starts overwrite within the month of
                # the window START's Monday
                out[(p, (w.year, w.month))] = beta
            w += timedelta(weeks=1)
    return out


def _frames(stock_rows, index_rows):
    crsp_d = pd.DataFrame(
        [
            {"permno": p, "dlycaldt": pd.Timestamp(d), "retx": np.nan if _is_null(r) else r}
            for p, d, r in stock_rows
        ]
    )
    crsp_index_d = pd.DataFrame(
        [
            {"caldt": pd.Timestamp(d), "vwretx": np.nan if _is_null(v) else v}
            for d, v in sorted(index_rows.items())
        ]
    )
    return crsp_d, crsp_index_d


def _kernel_betas(stock_rows, index_rows, months):
    from fm_returnprediction_tpu.ops.daily_kernels import weekly_rolling_beta_monthly
    from fm_returnprediction_tpu.panel.daily import build_daily_panel

    crsp_d, crsp_index_d = _frames(stock_rows, index_rows)
    dp = build_daily_panel(crsp_d, crsp_index_d, months)
    beta = np.asarray(
        weekly_rolling_beta_monthly(
            jnp.asarray(dp.ret),
            jnp.asarray(dp.mask),
            jnp.asarray(dp.mkt),
            jnp.asarray(dp.week_id),
            dp.n_weeks,
            jnp.asarray(dp.week_month_id),
            dp.n_months,
            window_weeks=WINDOW_WEEKS,
            mkt_present=jnp.asarray(dp.mkt_present),
        )
    )
    got = {}
    month_keys = [((m.year, m.month)) for m in pd.DatetimeIndex(months)]
    for j, permno in enumerate(dp.ids):
        for i, mk in enumerate(month_keys):
            got[(int(permno), mk)] = beta[i, j]
    return got


def _compare(stock_rows, index_rows, months):
    want = oracle_weekly_betas(stock_rows, index_rows)
    got = _kernel_betas(stock_rows, index_rows, months)
    checked = 0
    for key, w in want.items():
        assert key in got, f"kernel emitted nothing for {key}"
        g = got[key]
        if w is None:
            assert not np.isfinite(g), f"{key}: oracle null, kernel {g}"
        else:
            assert np.isfinite(g), f"{key}: oracle {w}, kernel non-finite"
            np.testing.assert_allclose(g, w, rtol=1e-6, atol=1e-10, err_msg=str(key))
        checked += 1
    # and the kernel must not invent betas in months the oracle has none
    oracle_months = set(want)
    for key, g in got.items():
        if key not in oracle_months:
            assert not np.isfinite(g), f"kernel invented beta at {key}: {g}"
    return checked


def _month_ends(start_year, start_month, end_year, end_month):
    return np.asarray(
        pd.date_range(
            pd.Timestamp(year=start_year, month=start_month, day=1),
            pd.Timestamp(year=end_year, month=end_month, day=28) + pd.offsets.MonthEnd(0),
            freq="ME",
        )
    )


@pytest.fixture(scope="module")
def adversarial_universe():
    """Hand-built calendars exercising every clause of the contract."""
    rng = np.random.default_rng(19640131)
    index_rows = {}
    # trading days: weekdays 1999-11-01..2003-06-30, with holiday MONDAYS
    # (first Monday of Sep, some Jan 1st-week Mondays) and a few fully
    # missing index days (firm rows there must drop via the inner join)
    d = date(1999, 11, 1)
    holidays = {date(2000, 9, 4), date(2001, 9, 3), date(2001, 1, 1),
                date(2002, 12, 30)}  # incl. a year-boundary-week Monday
    missing_index = {date(2000, 3, 15), date(2001, 7, 11), date(2002, 2, 6)}
    while d <= date(2003, 6, 30):
        if d.weekday() < 5 and d not in holidays and d not in missing_index:
            index_rows[d] = float(rng.normal(3e-4, 0.01))
        d += timedelta(days=1)
    # one present-but-null market value
    index_rows[date(2000, 6, 14)] = None

    trading_days = sorted(index_rows)
    stock_rows = []

    def add_firm(permno, first, last, beta, null_frac=0.0, skip=()):
        for dd in trading_days:
            if first <= dd <= last and dd not in skip:
                m = index_rows[dd]
                base = 0.0 if _is_null(m) else beta * m
                r = base + float(rng.normal(0, 0.02))
                if null_frac and rng.random() < null_frac:
                    r = None
                stock_rows.append((permno, dd, r))

    # A: born Wednesday, dies Tuesday, spans year boundaries, has null retx
    add_firm(101, date(1999, 11, 3), date(2002, 1, 8), 1.2, null_frac=0.05)
    # B: short life with two whole missing weeks (delisting gap)
    gap = {dd for dd in trading_days if date(2000, 5, 8) <= dd <= date(2000, 5, 19)}
    add_firm(102, date(2000, 4, 12), date(2000, 7, 21), 0.7, skip=gap)
    # C: a single trading day (every window has n == 1 → null beta)
    stock_rows.append((103, date(2001, 3, 7), 0.013))
    # D: long healthy firm covering the whole sample
    add_firm(104, date(1999, 11, 1), date(2003, 6, 30), 1.6)
    # E: alive only around a year boundary ISO week (Dec 29 2002 week)
    add_firm(105, date(2002, 12, 16), date(2003, 1, 17), 0.9)
    # F: rows also on the missing-index days (must be dropped by the join)
    add_firm(106, date(2001, 6, 1), date(2001, 8, 31), 1.1)
    for dd in sorted(missing_index):
        stock_rows.append((106, dd, 0.01))

    months = _month_ends(1999, 11, 2003, 6)
    return stock_rows, index_rows, months


def test_kernel_matches_independent_calendar_oracle(adversarial_universe):
    stock_rows, index_rows, months = adversarial_universe
    checked = _compare(stock_rows, index_rows, months)
    # every firm-month with an emitted window start must have been compared
    assert checked > 60, f"only {checked} firm-months checked — fixture too thin"


def test_year_boundary_week_stamps_december(adversarial_universe):
    """A window starting Monday 2002-12-30 labels DECEMBER 2002 even though
    most of its first week's days fall in January 2003 — the misread the
    oracle exists to catch. Firm E trades through that week."""
    stock_rows, index_rows, months = adversarial_universe
    want = oracle_weekly_betas(stock_rows, index_rows)
    got = _kernel_betas(stock_rows, index_rows, months)
    key = (105, (2002, 12))
    assert key in want and want[key] is not None
    np.testing.assert_allclose(got[key], want[key], rtol=1e-6)


def test_holiday_monday_still_anchors_on_monday():
    """One firm, one week whose Monday is a holiday (first row Tuesday).
    Lattice anchoring must still label the week by its MONDAY; anchoring on
    the first observation (a Tuesday) would shift every window start."""
    index_rows = {}
    rng = np.random.default_rng(7)
    d = date(2000, 1, 3)
    while d <= date(2000, 3, 31):
        if d.weekday() < 5 and d != date(2000, 1, 31):  # holiday Monday Jan 31
            index_rows[d] = float(rng.normal(0, 0.01))
        d += timedelta(days=1)
    stock_rows = [
        (7, dd, float(rng.normal(0, 0.02))) for dd in sorted(index_rows)
        if dd >= date(2000, 1, 31)
    ]
    months = _month_ends(2000, 1, 2000, 6)
    # first observed day is Tue 2000-02-01; its week's Monday is Jan 31 →
    # the first window start must stamp JANUARY
    want = oracle_weekly_betas(stock_rows, index_rows)
    assert (7, (2000, 1)) in want and want[(7, (2000, 1))] is not None
    _compare(stock_rows, index_rows, months)


def test_null_rows_count_in_denominator():
    """Two finite rows + one null-retx row in the same window: n must be 3
    (all rows), not 2 — the polars pl.count() clause. A kernel that counted
    only finite rows would shift beta."""
    index_rows = {
        date(2000, 1, 3): 0.010,
        date(2000, 1, 4): -0.020,
        date(2000, 1, 5): 0.015,
    }
    stock_rows = [
        (9, date(2000, 1, 3), 0.02),
        (9, date(2000, 1, 4), None),
        (9, date(2000, 1, 5), -0.01),
    ]
    months = _month_ends(2000, 1, 2000, 3)
    want = oracle_weekly_betas(stock_rows, index_rows)
    beta = want[(9, (2000, 1))]
    # hand-check the oracle itself: n=3 in the denominator
    ri = [math.log1p(0.02), math.log1p(-0.01)]
    rm = [math.log1p(v) for v in (0.010, -0.020, 0.015)]
    both = ri[0] * rm[0] + ri[1] * rm[2]
    n = 3
    cov = both - sum(ri) * sum(rm) / n
    var = sum(v * v for v in rm) - sum(rm) ** 2 / n
    np.testing.assert_allclose(beta, cov / var, rtol=1e-12)
    _compare(stock_rows, index_rows, months)
