"""Backtest subsystem differentials (ISSUE 18).

The contracts under test:

- the SCAN route (batched per-month solve + masked prefix sums) matches
  the per-origin full-refit ORACLE exactly — f64 ≤ 1e-13 / f32 ≤ 1e-6 —
  for expanding AND rolling windows, under OLS AND the FWL estimator;
- out-of-sample predictions are strictly past (origin t−1 forecasts
  month t; month 0 never forecasts) and reproduce the coefficient-path
  einsum by hand;
- OOS R² / IC / rank-IC device kernels match their numpy host oracles;
- quantile assignment matches a pandas-qcut-style numpy oracle on the
  same linear-interpolation breakpoints, INCLUDING tie months (equal
  forecasts land in the same bucket deterministically); per-bucket
  returns, counts and one-way turnover match the oracle too;
- the circular-block bootstrap's draw 0 is the never-resampled point
  estimate (≡ ``series_inference``'s mean);
- a full sweep (2 schemes × ew/vw) answers ENTIRELY from the bank:
  the panel-contraction ledger delta is 0;
- the loadgen portfolio consumer's fleet-served quotes are bit-identical
  to the batch executor's predictions, with a clean journal replay;
- every non-composing input is rejected LOUDLY (iv/absorb/pooled
  estimators, bad schemes/routes/sinks/weightings, vw without weights);
- the ``FMRP_BACKTEST_*`` knobs resolve argument > env > default.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fm_returnprediction_tpu.backtest import (
    MetricsSink,
    backtest_paths,
    backtest_space,
    bootstrap_series,
    ic_series,
    ic_series_np,
    oos_r2,
    oos_r2_np,
    parse_scheme,
    predict_er,
    quantile_sorts,
    resolve_backtest_route,
    resolve_backtest_sink,
    resolve_backtest_sink_name,
    resolve_quantiles,
    resolve_schemes,
    run_backtest,
    run_backtest_scenarios,
    series_inference,
)
from fm_returnprediction_tpu.backtest.space import BacktestSpace
from fm_returnprediction_tpu.specgrid.cellspace import CellSpace
from fm_returnprediction_tpu.specgrid.grambank import build_bank

pytestmark = [pytest.mark.backtest]

X64 = bool(jax.config.jax_enable_x64)
TOL = 1e-13 if X64 else 1e-6        # scan-vs-refit (summation order only)
ORACLE_TOL = 1e-10 if X64 else 1e-5  # device kernel vs float64 host oracle


def _panel(seed=0, t=30, n=140, p=4):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p))
    x[rng.random(x.shape) < 0.06] = np.nan
    beta = rng.standard_normal(p) * 0.1
    y = np.nansum(x * beta, axis=-1) + 0.3 * rng.standard_normal((t, n))
    y[rng.random(y.shape) < 0.1] = np.nan
    masks = {
        "All": np.ones((t, n), bool),
        "Big": (rng.random(n) > 0.35)[None, :] & np.ones((t, n), bool),
    }
    return y, x, masks


@pytest.fixture(scope="module")
def bank():
    y, x, masks = _panel()
    names = tuple(f"c{i}" for i in range(x.shape[-1]))
    space = CellSpace(
        regressor_sets=(("m2", names[:2]), ("mfull", names)),
        universes=("All", "Big"),
        windows=(("full", None),),
        nw_lags=4, min_months=8,
    )
    bk = build_bank(y, x, masks, space, fingerprint="test-backtest")
    return bk, (y, x, masks)


# -- scan route ≡ refit oracle ----------------------------------------------

@pytest.mark.parametrize("scheme", ["expanding", "rolling12"])
@pytest.mark.parametrize("estimator", [None, "fwl:c0"])
def test_scan_matches_refit_oracle(bank, scheme, estimator):
    """The fused prefix-sum program and the per-origin full-refit loop
    are the same numbers up to summation order — exact by Gram
    additivity, for both window schemes and both composing estimators."""
    bk, _ = bank
    scan = backtest_paths(bk, scheme, estimator=estimator, route="scan",
                          min_months=5)
    refit = backtest_paths(bk, scheme, estimator=estimator, route="refit",
                           min_months=5)
    assert scan.route == "scan" and refit.route == "refit"
    assert scan.path.shape == refit.path.shape
    np.testing.assert_array_equal(np.isnan(scan.path), np.isnan(refit.path))
    np.testing.assert_allclose(scan.path, refit.path, atol=TOL,
                               equal_nan=True)
    np.testing.assert_allclose(scan.count, refit.count, atol=TOL)
    np.testing.assert_array_equal(scan.month_valid, refit.month_valid)
    np.testing.assert_allclose(scan.beta, refit.beta, atol=TOL)
    np.testing.assert_array_equal(scan.col_sel, refit.col_sel)
    # paths exist somewhere (the panel is dense enough at this shape)
    assert np.isfinite(scan.path).any()


def test_rolling_path_is_prefix_difference(bank):
    """A rolling-W origin equals the masked mean over exactly the last W
    valid-month slots — pin one origin by hand against the per-month
    leaves the scan route itself returns."""
    bk, _ = bank
    w = 12
    paths = backtest_paths(bk, f"rolling{w}", route="scan", min_months=5)
    k, origin = 0, bk.n_months - 1
    lo = origin - w + 1
    sel = paths.month_valid[k, lo:origin + 1]
    want = paths.beta[k, lo:origin + 1][sel].mean(axis=0)
    np.testing.assert_allclose(paths.path[k, origin], want, atol=ORACLE_TOL)
    assert paths.count[k, origin] == sel.sum()


def test_fwl_paths_differ_from_ols_and_disclose_label(bank):
    bk, _ = bank
    ols = backtest_paths(bk, "expanding", route="scan", min_months=5)
    fwl = backtest_paths(bk, "expanding", estimator="fwl:c0", route="scan",
                         min_months=5)
    assert fwl.estimator_label == "fwl[c0]"
    assert ols.estimator_label == "ols"
    # the partialled solve drops the control from the solved selection
    assert fwl.col_sel.sum() < ols.col_sel.sum()
    # under FWL the residualized intercept is exactly 0 where defined
    finite = np.isfinite(fwl.path[..., 0])
    assert finite.any()
    np.testing.assert_allclose(fwl.path[..., 0][finite], 0.0, atol=TOL)


# -- prediction alignment ----------------------------------------------------

def test_predict_er_is_strictly_past(bank):
    """Month t's forecast is origin t−1's coefficient path applied to
    month t's characteristics; month 0 has no origin and never
    forecasts."""
    bk, (y, x, masks) = bank
    paths = backtest_paths(bk, "expanding", route="scan", min_months=5)
    pair = 1
    er, er_valid = predict_er(paths, x, masks["Big"], pair)
    assert not er_valid[0].any()
    sel = paths.col_sel[pair]
    t_probe = bk.n_months - 1
    rows = np.flatnonzero(er_valid[t_probe])
    assert rows.size
    coef = paths.path[pair, t_probe - 1]
    want = coef[0] + x[t_probe][rows][:, sel] @ coef[1:][sel]
    np.testing.assert_allclose(er[t_probe, rows], want, atol=ORACLE_TOL)
    # rows outside the universe or with a non-finite SELECTED predictor
    # never forecast
    assert not er_valid[:, ~masks["Big"][0].astype(bool)].any() \
        or masks["Big"].all()
    bad = ~np.isfinite(x[..., sel]).all(axis=-1)
    assert not (er_valid & bad).any()


# -- evaluation oracles ------------------------------------------------------

def test_oos_r2_matches_numpy_oracle(bank):
    bk, (y, x, masks) = bank
    paths = backtest_paths(bk, "expanding", route="scan", min_months=5)
    er, er_valid = predict_er(paths, x, masks["All"], pair=1)
    got = float(oos_r2(jnp.asarray(er), jnp.asarray(er_valid),
                       jnp.asarray(y)))
    want = oos_r2_np(er, er_valid, y)
    assert np.isfinite(want)
    np.testing.assert_allclose(got, want, atol=ORACLE_TOL)


def test_ic_series_matches_numpy_oracle_with_ties():
    """Pearson and rank IC vs the host mirror — the forecast panel is
    QUANTIZED so months carry heavy ties, pinning the ordinal (stable
    double-argsort) rank convention on both sides."""
    rng = np.random.default_rng(7)
    t, n = 25, 60
    er = np.round(rng.standard_normal((t, n)), 1)  # many exact ties
    realized = 0.4 * er + rng.standard_normal((t, n))
    er_valid = rng.random((t, n)) > 0.15
    realized[rng.random((t, n)) < 0.1] = np.nan
    ic, rank_ic, good = ic_series(jnp.asarray(er), jnp.asarray(er_valid),
                                  jnp.asarray(realized), min_obs=10)
    ic_np, rank_np = ic_series_np(er, er_valid, realized, min_obs=10)
    np.testing.assert_array_equal(np.isnan(np.asarray(ic)), np.isnan(ic_np))
    np.testing.assert_allclose(np.asarray(ic), ic_np, atol=ORACLE_TOL,
                               equal_nan=True)
    np.testing.assert_allclose(np.asarray(rank_ic), rank_np,
                               atol=ORACLE_TOL, equal_nan=True)
    assert np.asarray(good).sum() > t // 2


def test_series_inference_mean_and_tstat():
    rng = np.random.default_rng(3)
    series = rng.standard_normal(40) + 0.5
    series[[4, 17]] = np.nan
    mean, se, tstat, n = series_inference(series, nw_lags=4)
    ok = np.isfinite(series)
    assert n == ok.sum()
    np.testing.assert_allclose(mean, series[ok].mean(), atol=ORACLE_TOL)
    np.testing.assert_allclose(tstat, mean / se, atol=ORACLE_TOL)


# -- portfolio sorts vs numpy oracle ----------------------------------------

def _sorts_np(er, er_valid, realized, n_q, min_obs, weights=None):
    """Host oracle for ``quantile_sorts``: per-month np.quantile (linear)
    interior breakpoints, bucket = breakpoints strictly below the value
    (the pandas-qcut-style tie-deterministic assignment), normalized
    holdings, one-way turnover."""
    t, n = er.shape
    ok = np.asarray(er_valid, bool) & np.isfinite(realized)
    if weights is not None:
        ok = ok & np.isfinite(weights) & (weights > 0)
    month_valid = ok.sum(axis=1) >= min_obs
    qret = np.full((t, n_q), np.nan)
    counts = np.zeros((t, n_q), int)
    wnorm = np.zeros((t, n_q, n))
    qs = np.arange(1, n_q) / n_q
    for m in range(t):
        rows = np.flatnonzero(ok[m])
        if not rows.size:
            continue
        vals = er[m, rows]
        bp = np.quantile(vals, qs)
        bucket = (vals[:, None] > bp[None, :]).sum(axis=1)
        for d in range(n_q):
            sel = rows[bucket == d]
            counts[m, d] = sel.size
            if not sel.size:
                continue
            w = np.ones(sel.size) if weights is None else weights[m, sel]
            wn = w / w.sum()
            wnorm[m, d, sel] = wn
            if month_valid[m]:
                qret[m, d] = float(wn @ realized[m, sel])
    turnover = np.full((t, n_q), np.nan)
    for m in range(1, t):
        if not (month_valid[m] and month_valid[m - 1]):
            continue
        for d in range(n_q):
            if counts[m, d] and counts[m - 1, d]:
                turnover[m, d] = 0.5 * np.abs(
                    wnorm[m, d] - wnorm[m - 1, d]).sum()
    return qret, counts, month_valid, turnover


@pytest.mark.parametrize("value_weighted", [False, True])
def test_quantile_sorts_match_numpy_oracle(value_weighted):
    """Per-bucket returns, counts and turnover vs the host oracle — the
    forecast panel is quantized so TIE MONTHS (values sitting exactly on
    a breakpoint) are exercised, and the assignment must still agree."""
    rng = np.random.default_rng(11)
    t, n, n_q = 24, 90, 5
    er = np.round(rng.standard_normal((t, n)), 1)
    realized = 0.3 * er + rng.standard_normal((t, n))
    er_valid = rng.random((t, n)) > 0.1
    realized[rng.random((t, n)) < 0.08] = np.nan
    weights = np.abs(rng.lognormal(size=(t, n))) + 0.1
    weights[rng.random((t, n)) < 0.05] = np.nan  # VW drops unweightables

    port = quantile_sorts(
        jnp.asarray(er), jnp.asarray(er_valid), jnp.asarray(realized),
        weights=jnp.asarray(weights) if value_weighted else None,
        n_quantiles=n_q, min_obs=20,
        value_weighted=value_weighted,
    )
    qret, counts, month_valid, turnover = _sorts_np(
        er, er_valid, realized, n_q, min_obs=20,
        weights=weights if value_weighted else None,
    )
    # ties are real at this quantization: some month has a duplicated
    # forecast value spanning a would-be bucket edge
    assert any(np.unique(er[m, er_valid[m]]).size < er_valid[m].sum()
               for m in range(t))
    np.testing.assert_array_equal(np.asarray(port.month_valid), month_valid)
    np.testing.assert_array_equal(np.asarray(port.counts), counts)
    np.testing.assert_allclose(np.asarray(port.quantile_returns), qret,
                               atol=ORACLE_TOL, equal_nan=True)
    np.testing.assert_allclose(np.asarray(port.turnover), turnover,
                               atol=ORACLE_TOL, equal_nan=True)
    # summary internal consistency: spread series/mean/tstat tie together
    spread_series = np.asarray(port.spread_series)
    usable = month_valid & (counts > 0).all(axis=1)
    sv = usable & np.isfinite(spread_series)
    np.testing.assert_allclose(
        spread_series[month_valid],
        (qret[:, -1] - qret[:, 0])[month_valid],
        atol=ORACLE_TOL, equal_nan=True)
    np.testing.assert_allclose(float(port.spread), spread_series[sv].mean(),
                               atol=ORACLE_TOL)
    assert int(port.n_months) == sv.sum()
    np.testing.assert_allclose(
        float(port.spread_tstat),
        float(port.spread) / float(port.spread_nw_se), atol=ORACLE_TOL)


def test_equal_forecasts_share_a_bucket():
    """Tie determinism directly: a month whose values are all drawn from
    3 distinct levels puts every copy of a level in one bucket."""
    t, n, n_q = 4, 30, 3
    rng = np.random.default_rng(5)
    levels = np.array([-1.0, 0.0, 1.0])
    er = levels[rng.integers(0, 3, size=(t, n))]
    realized = rng.standard_normal((t, n))
    ok = np.ones((t, n), bool)
    port = quantile_sorts(jnp.asarray(er), jnp.asarray(ok),
                          jnp.asarray(realized), n_quantiles=n_q, min_obs=5)
    counts = np.asarray(port.counts)
    assert (counts.sum(axis=1) == n).all()
    for m in range(t):
        # buckets are monotone in the forecast and a level is NEVER
        # split: every bucket-count prefix sum must land on a level-group
        # boundary of the sorted cross-section
        sizes = [(er[m] == lev).sum() for lev in levels]
        boundaries = set(np.concatenate([[0], np.cumsum(sizes)]))
        assert set(np.cumsum(counts[m])) <= boundaries


# -- bootstrap ---------------------------------------------------------------

def test_bootstrap_draw0_is_the_point_estimate():
    rng = np.random.default_rng(9)
    series = rng.standard_normal(36) * 0.02 + 0.01
    series[[0, 13]] = np.nan
    point, boot_se, draw_means = bootstrap_series(series, draws=16, seed=3,
                                                  block=6)
    mean, _, _, _ = series_inference(series)
    np.testing.assert_allclose(point[0], mean,
                               atol=1e-12 if X64 else 1e-6)
    assert draw_means.shape == (15, 1)
    assert np.isfinite(boot_se).all() and (boot_se > 0).all()
    # a multi-column series shares one gather plan
    two = np.stack([series, 2 * series], axis=1)
    p2, se2, dm2 = bootstrap_series(two, draws=16, seed=3, block=6)
    assert dm2.shape == (15, 2)
    np.testing.assert_allclose(p2[1], 2 * p2[0], atol=1e-12 if X64 else 1e-5)
    # draws=1 is the bare point, no resamples
    p1, se1, dm1 = bootstrap_series(series, draws=1)
    np.testing.assert_allclose(p1[0], mean, atol=1e-12 if X64 else 1e-6)
    assert dm1.shape == (0, 1) and np.isnan(se1).all()
    with pytest.raises(ValueError, match="draws"):
        bootstrap_series(series, draws=0)


# -- the sweep: bank-answered, ledger-proven ---------------------------------

def test_sweep_answers_from_bank_with_zero_contractions(bank):
    """A full 2-scheme × 2-weighting sweep emits one row per cell and
    never contracts the (T, N, P) panel — the acceptance ledger proof."""
    bk, (y, x, masks) = bank
    rng = np.random.default_rng(2018)
    weights = np.abs(rng.lognormal(size=y.shape)) + 0.1
    space = backtest_space(bk, schemes="expanding,rolling12",
                           weightings=("ew", "vw"), n_quantiles=5,
                           min_obs=20)
    frame, stats = run_backtest(bk, x, y, masks, space=space,
                                weights_var=weights, min_months=5,
                                bootstrap=8, seed=1)
    assert stats["panel_contractions"] == 0
    assert len(frame) == len(space) == stats["rows_seen"]
    # one path solve per (scheme, estimator) digit — the one-slot memo
    assert stats["path_solves"] == len(space.schemes)
    assert stats["predict_calls"] == len(space.schemes) * space.n_pairs
    for col in ("cell", "scheme", "set", "universe", "weighting", "oos_r2",
                "ic_mean", "ic_tstat", "rank_ic_mean", "spread",
                "spread_tstat", "spread_turnover", "n_months",
                "spread_boot_se"):
        assert col in frame.columns, col
    assert set(frame["scheme"]) == {"expanding", "rolling12"}
    assert set(frame["weighting"]) == {"ew", "vw"}
    assert frame["cell"].is_unique
    assert np.isfinite(frame["spread"]).all()
    assert np.isfinite(frame["spread_boot_se"]).all()
    # turnover is a [0, 1] fraction wherever defined
    tau = frame["spread_turnover"].to_numpy()
    assert ((tau >= 0) & (tau <= 1))[np.isfinite(tau)].all()


def test_metrics_sink_aggregates_per_group(bank):
    """The O(1) metrics sink reproduces a pandas groupby of the full
    frame — mean/std per (scheme, weighting) plus the |spread_tstat|
    best cell with the lower-index tie-break."""
    bk, (y, x, masks) = bank
    rng = np.random.default_rng(2018)
    weights = np.abs(rng.lognormal(size=y.shape)) + 0.1
    space = backtest_space(bk, schemes="expanding,rolling12",
                           weightings=("ew", "vw"), n_quantiles=5,
                           min_obs=20)
    frame, _ = run_backtest(bk, x, y, masks, space=space,
                            weights_var=weights, min_months=5)
    sink = MetricsSink()
    sink.consume(frame)
    out = sink.finish().set_index(["scheme", "weighting"])
    assert len(out) == 4
    grouped = frame.groupby(["scheme", "weighting"])
    for key, grp in grouped:
        row = out.loc[key]
        assert row["cells"] == len(grp)
        np.testing.assert_allclose(row["spread_mean"], grp["spread"].mean(),
                                   atol=ORACLE_TOL)
        np.testing.assert_allclose(row["spread_std"], grp["spread"].std(),
                                   atol=ORACLE_TOL)
        best = grp.loc[grp["spread_tstat"].abs().idxmax()]
        assert row["best_cell"] == best["cell"]


def test_scenarios_entrypoint_vw_reduction_and_stats():
    """``run_backtest_scenarios`` (the pipeline's stage): with a weight
    column VW cells run; without one they reduce to EW with the
    reduction disclosed; a VW-only request without weights is loud."""
    from fm_returnprediction_tpu.models.lewellen import ModelSpec

    y, x, masks = _panel(seed=21, t=24, n=80, p=3)
    names = ["c0", "c1", "c2"]
    me = np.abs(np.random.default_rng(4).lognormal(size=y.shape)) + 0.1

    class _MiniPanel:
        def __init__(self, with_me):
            self.mask = masks["All"]
            self.months = np.arange(y.shape[0])
            self.var_names = ["retx"] + names + (["me"] if with_me else [])

        def var(self, name):
            return {"retx": y, "me": me}[name]

        def select(self, cols):
            return x[:, :, [names.index(c) for c in cols]]

    variables = {"V0": "c0", "V1": "c1", "V2": "c2"}
    models = [ModelSpec("Model A", ["V0", "V1"]),
              ModelSpec("Model B", ["V0", "V1", "V2"])]
    frame, stats = run_backtest_scenarios(
        _MiniPanel(True), masks, variables, models=models,
        schemes="expanding,rolling8", n_quantiles=4, min_obs=15,
        min_months=5, return_stats=True,
    )
    assert stats["panel_contractions"] == 0
    assert not stats["weighting_reduced"]
    assert set(frame["weighting"]) == {"ew", "vw"}
    assert len(frame) == 2 * 2 * 2 * 2  # scheme × model × universe × wgt

    reduced, rstats = run_backtest_scenarios(
        _MiniPanel(False), masks, variables, models=models,
        schemes="expanding", n_quantiles=4, min_obs=15, min_months=5,
        return_stats=True,
    )
    assert rstats["weighting_reduced"]
    assert set(reduced["weighting"]) == {"ew"}

    with pytest.raises(ValueError, match="weight column"):
        run_backtest_scenarios(_MiniPanel(False), masks, variables,
                               models=models, weightings=("vw",),
                               min_months=5)


# -- fleet-served portfolio consumer -----------------------------------------

def test_portfolio_consumer_quotes_match_batch_executor(tmp_path):
    """Every quote the loadgen portfolio consumer received THROUGH the
    fleet's front door is bit-identical to the batch executor's answer
    for the same (month, features), the journal replays clean, and the
    formed long/short books follow the tie-deterministic convention."""
    from fm_returnprediction_tpu.serving import (
        BucketedExecutor,
        ServingFleet,
        build_serving_state,
        portfolio_consumer,
        replay_journal,
    )

    t, n, p = 48, 40, 3
    rng = np.random.default_rng(2015)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = np.array([0.05, -0.02, 0.01], np.float32)
    y = (x @ beta + 0.02 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.1
    y = np.where(mask, y, np.nan).astype(np.float32)
    x = np.where(mask[..., None], x, np.nan).astype(np.float32)
    state = build_serving_state(y, x, mask, window=16, min_periods=8)
    months = np.flatnonzero(state.have_coef())[-3:]
    assert months.size == 3

    journal = tmp_path / "consumer.jsonl"
    # min_bucket=2 keeps a timing-dependent singleton microbatch off the
    # scalar bucket-1 program, whose reduction rounds one ULP differently
    # from every vectorized bucket — buckets >= 2 are one bit-identical
    # family, which is exactly the contract this differential pins
    with ServingFleet(state, 2, max_batch=16, max_latency_ms=1.0,
                      min_bucket=2, journal=journal) as fleet:
        report = portfolio_consumer(fleet, months, x[months], n_quantiles=4)
    assert report["phase"] == "portfolio_consumer"
    assert report["shed"] == 0 and report["errors"] == 0
    assert report["ok"] + report["degraded"] == report["n"]
    replay = replay_journal(journal)
    assert replay.clean, (replay.dropped, replay.duplicated, replay.invalid)

    # batch oracle: the executor answers the same (month, row) pairs
    valid = np.isfinite(x[months]).all(axis=-1)
    ex = BucketedExecutor(state, max_batch=64)
    want = np.full((months.size, n), np.nan)
    for i, m in enumerate(months):
        rows = np.flatnonzero(valid[i])
        got = ex.run(np.full(rows.size, m, np.int32), x[m, rows])
        want[i, rows] = got
    np.testing.assert_array_equal(report["quotes"], want)

    # formed books: long = top bucket, short = bottom, EW, disjoint
    assert report["months_formed"] == months.size
    lw, sw = report["long_weights"], report["short_weights"]
    for i in range(months.size):
        assert not (lw[i] > 0)[sw[i] > 0].any()
        np.testing.assert_allclose(lw[i].sum(), 1.0, atol=1e-9)
        np.testing.assert_allclose(sw[i].sum(), 1.0, atol=1e-9)
        top = lw[i] > 0
        assert np.nanmin(report["quotes"][i][top]) >= \
            np.nanmax(report["quotes"][i][sw[i] > 0])
    assert report["turnover_mean"] is not None
    assert 0.0 <= report["turnover_mean"] <= 1.0


# -- loud rejections ---------------------------------------------------------

def test_non_composing_estimators_rejected_loudly(bank):
    bk, _ = bank
    for est in ("pooled", "iv:c0~c1", "absorb:c1"):
        with pytest.raises(ValueError, match="not available here"):
            backtest_paths(bk, "expanding", estimator=est)
        with pytest.raises(ValueError, match="slope path"):
            backtest_space(bk, estimators=(est,))


def test_fwl_controls_must_be_banked_in_every_pair(bank):
    bk, _ = bank
    # c2 is contracted only into the mfull pairs, not the m2 pairs
    with pytest.raises(ValueError, match="every banked pair"):
        backtest_paths(bk, "expanding", estimator="fwl:c2")
    with pytest.raises(KeyError, match="union"):
        backtest_paths(bk, "expanding", estimator="fwl:zzz")


def test_malformed_inputs_rejected_loudly(bank):
    bk, (y, x, masks) = bank
    with pytest.raises(ValueError, match="expanding.*rolling"):
        parse_scheme("weekly")
    with pytest.raises(ValueError, match="W >= 1"):
        parse_scheme("rolling0")
    with pytest.raises(ValueError, match="route"):
        resolve_backtest_route("bogus")
    with pytest.raises(ValueError, match=">= 2"):
        resolve_quantiles(1)
    with pytest.raises(ValueError, match="repeat"):
        resolve_schemes("expanding,expanding")
    with pytest.raises(ValueError, match="unknown backtest sink"):
        resolve_backtest_sink_name("bogus")
    with pytest.raises(ValueError, match="weightings"):
        BacktestSpace(schemes=("expanding",), sets=("m2",),
                      universes=("All",), weightings=("equal",))
    with pytest.raises(ValueError, match=">= 2"):
        backtest_space(bk, n_quantiles=1)
    space = backtest_space(bk, schemes="expanding", weightings=("vw",))
    with pytest.raises(ValueError, match="weights_var"):
        run_backtest(bk, x, y, masks, space=space)
    with pytest.raises(KeyError, match="universe masks"):
        run_backtest(bk, x, y, {"All": masks["All"]},
                     space=backtest_space(bk, schemes="expanding",
                                          weightings=("ew",)))


# -- knob resolution ---------------------------------------------------------

def test_backtest_knobs_resolve_arg_over_env_over_default(monkeypatch):
    for var in ("FMRP_BACKTEST_ROUTE", "FMRP_BACKTEST_SCHEMES",
                "FMRP_BACKTEST_QUANTILES", "FMRP_BACKTEST_SINK"):
        monkeypatch.delenv(var, raising=False)
    # defaults
    assert resolve_backtest_route(None) == "auto"
    assert resolve_schemes(None) == (("expanding", None), ("rolling120", 120))
    assert resolve_quantiles(None) == 10
    assert resolve_backtest_sink_name(None) == "frame"
    # env wins over default
    monkeypatch.setenv("FMRP_BACKTEST_ROUTE", "refit")
    monkeypatch.setenv("FMRP_BACKTEST_SCHEMES", "rolling24")
    monkeypatch.setenv("FMRP_BACKTEST_QUANTILES", "5")
    monkeypatch.setenv("FMRP_BACKTEST_SINK", "metrics")
    assert resolve_backtest_route(None) == "refit"
    assert resolve_schemes(None) == (("rolling24", 24),)
    assert resolve_quantiles(None) == 5
    assert resolve_backtest_sink_name(None) == "metrics"
    assert isinstance(resolve_backtest_sink(None), MetricsSink)
    # explicit argument wins over env
    assert resolve_backtest_route("scan") == "scan"
    assert resolve_schemes("expanding") == (("expanding", None),)
    assert resolve_quantiles(3) == 3
    assert resolve_backtest_sink_name("frame") == "frame"
    # a poisoned env is loud, not silently defaulted
    monkeypatch.setenv("FMRP_BACKTEST_ROUTE", "nope")
    with pytest.raises(ValueError, match="route"):
        resolve_backtest_route(None)
