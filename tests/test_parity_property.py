"""Property-based differential tests for the parity-critical primitives.

Random masks/values against the semantics oracles: ``masked_quantile`` vs
``np.nanquantile`` (pandas-linear interpolation), and the compaction
machinery (`compact`/`lag`/`scatter_back`) vs pandas ``groupby.shift`` —
the row-semantics layer every characteristic rides on (SURVEY §7 hard
part (b)). Small example counts; hypothesis shrinks failures.
"""

import numpy as np
import pandas as pd
import pytest

pytest.importorskip("hypothesis")  # tier-1 must COLLECT cleanly without the optional dep
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.compaction import (
    compact,
    lag,
    make_compaction,
    scatter_back,
)
from fm_returnprediction_tpu.ops.quantiles import masked_quantile


@st.composite
def _panels(draw):
    t = draw(st.integers(min_value=1, max_value=24))
    n = draw(st.integers(min_value=1, max_value=10))
    mask_frac = draw(st.floats(min_value=0.0, max_value=1.0))
    nan_frac = draw(st.floats(min_value=0.0, max_value=0.4))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    return t, n, mask_frac, nan_frac, seed


def _make(t, n, mask_frac, nan_frac, seed):
    rng = np.random.default_rng(seed)
    values = rng.standard_normal((t, n))
    values[rng.random((t, n)) < nan_frac] = np.nan
    mask = rng.random((t, n)) < mask_frac
    return values, mask


@settings(max_examples=30, deadline=None)
@given(_panels(), st.sampled_from([0.01, 0.2, 0.5, 0.8, 0.99]))
def test_masked_quantile_matches_numpy(panel, q):
    t, n, mask_frac, nan_frac, seed = panel
    values, mask = _make(t, n, mask_frac, nan_frac, seed)
    got = np.asarray(masked_quantile(jnp.asarray(values.T), jnp.asarray(mask.T), q))
    want = np.full(n, np.nan)
    for i in range(n):
        row = values[:, i][mask[:, i]]
        row = row[np.isfinite(row)]
        if len(row):
            want[i] = np.quantile(row, q)  # linear interpolation default
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


@settings(max_examples=30, deadline=None)
@given(_panels(), st.integers(min_value=0, max_value=5))
def test_compacted_lag_matches_groupby_shift(panel, k):
    t, n, mask_frac, nan_frac, seed = panel
    values, mask = _make(t, n, mask_frac, nan_frac, seed)
    values = np.where(mask, values, np.nan)

    plan = make_compaction(jnp.asarray(mask))
    comp = compact(jnp.asarray(values), plan)
    got = np.asarray(scatter_back(lag(comp, k), plan))

    # pandas oracle: long frame per firm, shift over observed rows
    want = np.full((t, n), np.nan)
    for i in range(n):
        rows = np.flatnonzero(mask[:, i])
        if len(rows) == 0:
            continue
        shifted = pd.Series(values[rows, i]).shift(k).to_numpy()
        want[rows, i] = shifted
    np.testing.assert_allclose(got, want, rtol=0, atol=0, equal_nan=True)


@settings(max_examples=20, deadline=None)
@given(_panels())
def test_compact_scatter_roundtrip(panel):
    t, n, mask_frac, nan_frac, seed = panel
    values, mask = _make(t, n, mask_frac, nan_frac, seed)
    plan = make_compaction(jnp.asarray(mask))
    back = np.asarray(scatter_back(compact(jnp.asarray(values), plan), plan))
    want = np.where(mask, values, np.nan)
    np.testing.assert_allclose(back, want, rtol=0, atol=0, equal_nan=True)


@settings(max_examples=30, deadline=None)
@given(_panels(), st.integers(min_value=1, max_value=3),
       st.floats(min_value=0.0, max_value=0.1))
def test_table1_stats_multi_matches_pandas(panel, k, inf_frac):
    """The single-traversal Table 1 route vs the pandas oracle over random
    shapes, masks, NaN densities, and ±inf contamination (the reference
    treats ±inf as missing, ``src/calc_Lewellen_2014.py:625``)."""
    from fm_returnprediction_tpu.reporting.table1 import table1_stats_multi

    t, n, mask_frac, nan_frac, seed = panel
    rng = np.random.default_rng(seed)
    values = rng.standard_normal((t, n, k))
    values[rng.random((t, n, k)) < nan_frac] = np.nan
    pos = rng.random((t, n, k))
    values[pos < inf_frac / 2] = np.inf
    values[(pos >= inf_frac / 2) & (pos < inf_frac)] = -np.inf
    masks = rng.random((2, t, n)) < mask_frac

    avg, std, n_d = table1_stats_multi(jnp.asarray(values), jnp.asarray(masks))
    for si in range(2):
        rows = []
        for kk in range(k):
            v = np.where(masks[si], values[:, :, kk], np.nan)
            v = np.where(np.isfinite(v), v, np.nan)
            df = pd.DataFrame(v)  # rows = months, cols = firms
            m = df.mean(axis=1, skipna=True)       # monthly CS mean
            s = df.std(axis=1, ddof=1, skipna=True)
            rows.append((
                m.mean(skipna=True),               # time-series averages
                s.mean(skipna=True),
                int((df.notna().any(axis=0)).sum()),  # distinct firms
            ))
        want_avg = np.array([r[0] for r in rows])
        want_std = np.array([r[1] for r in rows])
        want_n = np.array([r[2] for r in rows])
        np.testing.assert_allclose(np.asarray(avg)[si], want_avg,
                                   rtol=1e-8, atol=1e-10, equal_nan=True)
        np.testing.assert_allclose(np.asarray(std)[si], want_std,
                                   rtol=1e-8, atol=1e-10, equal_nan=True)
        np.testing.assert_array_equal(np.asarray(n_d)[si], want_n)
