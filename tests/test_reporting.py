"""End-to-end reporting layer vs pandas oracles: subsets, Table 1, Table 2,
Figure 1 rolling slopes — on the same synthetic universe."""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from oracle import (
    oracle_fama_macbeth_summary,
    oracle_monthly_cs_ols,
    oracle_monthly_characteristics,
    oracle_std_12,
    oracle_weekly_beta,
    oracle_winsorize,
)

from fm_returnprediction_tpu.data.synthetic import SyntheticConfig, generate_synthetic_wrds
from fm_returnprediction_tpu.models.lewellen import FIGURE1_VARS, MODELS
from fm_returnprediction_tpu.panel.characteristics import FACTORS_DICT, get_factors
from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
from fm_returnprediction_tpu.panel.transform_compustat import (
    add_report_date,
    calc_book_equity,
    expand_compustat_annual_to_monthly,
    merge_CRSP_and_Compustat,
)
from fm_returnprediction_tpu.panel.transform_crsp import calculate_market_equity
from fm_returnprediction_tpu.reporting.figure1 import rolling_slopes
from fm_returnprediction_tpu.reporting.table1 import build_table_1
from fm_returnprediction_tpu.reporting.table2 import build_table_2, run_model_fm


@pytest.fixture(scope="module")
def world():
    wrds = generate_synthetic_wrds(SyntheticConfig(n_firms=40, n_months=84))
    crsp = calculate_market_equity(wrds["crsp_m"])
    comp = expand_compustat_annual_to_monthly(
        calc_book_equity(add_report_date(wrds["comp"].copy()))
    )
    merged = merge_CRSP_and_Compustat(crsp, comp, wrds["ccm"])
    merged["mthcaldt"] = merged["jdate"]
    panel, factors = get_factors(merged, wrds["crsp_d"], wrds["crsp_index_d"])
    masks = compute_subset_masks(panel)

    # oracle long panel with identical characteristic values
    df = oracle_monthly_characteristics(merged)
    df = oracle_std_12(wrds["crsp_d"], df)
    df = oracle_weekly_beta(wrds["crsp_d"], wrds["crsp_index_d"], df)
    df = oracle_winsorize(df, list(FACTORS_DICT.values()))

    # oracle subsets (reference get_subsets, src/calc_Lewellen_2014.py:44-112)
    nyse = df[df["primaryexch"] == "N"]
    pct = (
        nyse.groupby("mthcaldt")["me"].quantile([0.2, 0.5]).unstack(level=1)
        .rename(columns={0.2: "me_20", 0.5: "me_50"}).reset_index()
    )
    df = df.merge(pct, on="mthcaldt", how="left")
    oracle_subsets = {
        "All stocks": df,
        "All-but-tiny stocks": df[df["me"] >= df["me_20"]],
        "Large stocks": df[df["me"] >= df["me_50"]],
    }
    return panel, factors, masks, oracle_subsets


def test_subset_masks_match_oracle(world):
    panel, _, masks, oracle_subsets = world
    months = pd.DatetimeIndex(panel.months)
    for name, mask in masks.items():
        got = np.asarray(mask)
        want = oracle_subsets[name]
        want_keys = set(zip(want["permno"], want["jdate"]))
        got_keys = set()
        t_idx, n_idx = np.nonzero(got)
        for t, n in zip(t_idx, n_idx):
            got_keys.add((panel.ids[n], months[t]))
        assert got_keys == want_keys, name


def test_table_1_matches_oracle(world):
    panel, factors, masks, oracle_subsets = world
    table = build_table_1(panel, masks, factors)
    for subset_name, sub in oracle_subsets.items():
        for label, col in factors.items():
            clean = sub[[col, "mthcaldt", "permno"]].replace(
                [np.inf, -np.inf], np.nan
            ).dropna(subset=[col])
            if clean.empty:
                continue
            stats = clean.groupby("mthcaldt")[col].agg(["mean", "std"])
            np.testing.assert_allclose(
                table.loc[label, (subset_name, "Avg")], stats["mean"].mean(),
                rtol=1e-8, err_msg=f"{subset_name}/{label}/Avg",
            )
            np.testing.assert_allclose(
                table.loc[label, (subset_name, "Std")], stats["std"].mean(),
                rtol=1e-8, err_msg=f"{subset_name}/{label}/Std",
            )
            assert table.loc[label, (subset_name, "N")] == clean["permno"].nunique()


@pytest.mark.parametrize("model_idx", [0, 1, 2])
def test_table_2_fm_matches_oracle(world, model_idx):
    panel, factors, masks, oracle_subsets = world
    model = MODELS[model_idx]
    xvars = [factors[label] for label in model.predictors]
    for subset_name, sub in oracle_subsets.items():
        cs = oracle_monthly_cs_ols(sub, "retx", xvars)
        _, fm = run_model_fm(panel, masks[subset_name], model, factors)
        if cs.empty:
            # no month had enough complete-case rows: both sides must agree,
            # and the means must be NaN (empty .mean()) so Table 2 blanks them
            assert int(fm.n_months) == 0, subset_name
            assert np.isnan(np.asarray(fm.coef)).all()
            assert np.isnan(float(fm.mean_r2)) and np.isnan(float(fm.mean_n))
            continue
        want = oracle_fama_macbeth_summary(cs, xvars)
        for i, col in enumerate(xvars):
            np.testing.assert_allclose(
                float(fm.coef[i]), want[f"{col}_coef"], rtol=1e-6,
                err_msg=f"{subset_name}/{col}",
            )
            np.testing.assert_allclose(
                float(fm.tstat[i]), want[f"{col}_tstat"], rtol=1e-6,
                err_msg=f"{subset_name}/{col}/t",
            )
        np.testing.assert_allclose(float(fm.mean_r2), want["mean_R2"], rtol=1e-8)
        np.testing.assert_allclose(float(fm.mean_n), want["mean_N"], rtol=1e-12)


def test_table_2_layout_contract(world):
    panel, factors, masks, _ = world
    table = build_table_2(panel, masks, factors)
    # rows: each model block ends with N; columns: 3 subsets × 3 metrics
    assert list(table.columns.get_level_values(0).unique()) == [
        "All stocks", "All-but-tiny stocks", "Large stocks",
    ]
    assert list(table.columns.get_level_values(1).unique()) == ["Slope", "t-stat", "R^2"]
    for model in MODELS:
        block = table.loc[model.name]
        assert list(block.index) == model.predictors + ["N"]
        r2_col = block[("All stocks", "R^2")]
        assert r2_col.iloc[0] != ""  # first row shows R²
        assert (r2_col.iloc[1:] == "").all()  # rest blanked
        n_cell = block.loc["N", ("All stocks", "Slope")]
        assert isinstance(n_cell, str) and n_cell != ""


def test_figure1_rolling_slopes_match_oracle(world):
    panel, factors, masks, oracle_subsets = world
    xvars = list(FIGURE1_VARS.keys())
    for subset_name in ["All stocks", "Large stocks"]:
        sub = oracle_subsets[subset_name]
        cs = oracle_monthly_cs_ols(sub, "retx", xvars)
        slopes = cs.set_index("mthcaldt")[[f"slope_{v}" for v in xvars]]
        slopes.columns = xvars
        want = slopes.rolling(window=120, min_periods=60).mean()
        got = rolling_slopes(panel, masks[subset_name])
        assert got.index.equals(want.index)
        g, w = got.to_numpy(), want.to_numpy()
        both_nan = np.isnan(g) & np.isnan(w)
        np.testing.assert_allclose(
            np.where(both_nan, 0, g), np.where(both_nan, 0, w), rtol=1e-6, atol=1e-10
        )


def test_table1_multi_matches_two_pass(world):
    """``table1_stats_multi`` (single-traversal GEMM route, pivot-shifted
    one-pass variance) vs ``table1_stats`` (two-pass reference): the shift
    term must keep the cancellation-prone variance as accurate as the
    two-pass form, including on a near-constant cross-section."""
    import jax.numpy as jnp

    from fm_returnprediction_tpu.reporting.table1 import (
        table1_stats,
        table1_stats_multi,
    )

    panel, factors, masks, _ = world
    var_cols = [panel.var_index(col) for col in factors.values()]
    values = jnp.asarray(panel.values[:, :, var_cols])
    cases = [(values, masks)]

    # near-constant cross-sections: raw one-pass variance would lose ~all
    # significant digits here; the pivot-shifted form must not
    rng = np.random.default_rng(5)
    t, n = 24, 40
    nc = 7.25 + 1e-9 * rng.standard_normal((t, n, 2))
    nc[rng.random((t, n, 2)) < 0.1] = np.nan
    nc_masks = {
        "all": np.ones((t, n), bool),
        "half": np.broadcast_to(np.arange(n)[None, :] < n // 2, (t, n)),
    }
    cases.append((jnp.asarray(nc), nc_masks))

    for vals, mask_dict in cases:
        stacked = jnp.stack([jnp.asarray(m) for m in mask_dict.values()])
        avg_m, std_m, n_m = table1_stats_multi(vals, stacked)
        for si, m in enumerate(mask_dict.values()):
            avg, std, n_ = table1_stats(vals, jnp.asarray(m))
            np.testing.assert_allclose(np.asarray(avg_m)[si], np.asarray(avg),
                                       rtol=1e-10, atol=1e-12, equal_nan=True)
            np.testing.assert_allclose(np.asarray(std_m)[si], np.asarray(std),
                                       rtol=1e-6, atol=1e-15, equal_nan=True)
            np.testing.assert_array_equal(np.asarray(n_m)[si], np.asarray(n_))

    # production dtype: the TPU pipeline runs f32 — the f32 GEMM route must
    # stay within a few f32-eps of the f64 two-pass truth (well inside the
    # 1e-4 parity budget); this is where a precision regression in the
    # einsum contractions (bf16 operand truncation) would show as ~1e-3
    vals64, mask_dict = cases[0]
    stacked = jnp.stack([jnp.asarray(m) for m in mask_dict.values()])
    avg32, std32, n32 = table1_stats_multi(
        jnp.asarray(vals64, jnp.float32), stacked
    )
    for si, m in enumerate(mask_dict.values()):
        avg, std, n_ = table1_stats(vals64, jnp.asarray(m))
        np.testing.assert_allclose(np.asarray(avg32)[si], np.asarray(avg),
                                   rtol=2e-5, atol=1e-7, equal_nan=True)
        np.testing.assert_allclose(np.asarray(std32)[si], np.asarray(std),
                                   rtol=2e-4, atol=1e-6, equal_nan=True)
        np.testing.assert_array_equal(np.asarray(n32)[si], np.asarray(n_))


def test_split_route_compiles_once_per_model_shape(world, monkeypatch):
    """The Table 2 split route's claimed shape-caching must actually hit:
    9 (model, subset) cells may add at most one compiled program per
    DISTINCT model shape (3 here) — subsets share the (T, N, P) signature.
    The real-shape TPU cold-compile bill (~33 s/program over the tunnel)
    scales with this count, so a silent regression to per-cell compiles
    would triple it."""
    from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
    from fm_returnprediction_tpu.reporting.figure1 import (
        _subset_one_device,
        subset_sweep,
    )

    panel, factors, masks, _ = world
    # pin the pre-existing stacked-QR route: the fusion split policy only
    # exists there (the default Gram route has no stacked designs to split)
    monkeypatch.setenv("FMRP_SPECGRID_ROUTE", "stacked")
    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", "0")  # force the split route
    fama_macbeth.clear_cache()
    build_table_2(panel, masks, factors)
    assert fama_macbeth._cache_size() == 3
    # figure/decile family: all subsets share one (T, N, P) signature
    _subset_one_device.clear_cache()
    subset_sweep(panel, masks, list(masks))
    assert _subset_one_device._cache_size() == 1


def test_fusion_split_routes_match_fused(world, monkeypatch):
    """The large-shape per-cell/per-subset routes (reporting.fusion budget
    exceeded — the real-shape TPU compile fix) produce results identical to
    the fused subset-vmapped programs."""
    from fm_returnprediction_tpu.reporting.figure1 import subset_sweep

    panel, factors, masks, _ = world
    monkeypatch.setenv("FMRP_SPECGRID_ROUTE", "stacked")  # fusion policy path
    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", "1048576")  # force fused
    fused_t1 = build_table_1(panel, masks, factors)
    fused_t2 = build_table_2(panel, masks, factors)
    fused_sweep = subset_sweep(panel, masks, list(masks))

    monkeypatch.setenv("FMRP_FUSE_SUBSETS_MB", "0")  # force the split route
    split_t1 = build_table_1(panel, masks, factors)
    split_t2 = build_table_2(panel, masks, factors)
    split_sweep = subset_sweep(panel, masks, list(masks))

    pd.testing.assert_frame_equal(fused_t1, split_t1)
    pd.testing.assert_frame_equal(fused_t2, split_t2)
    assert list(fused_sweep) == list(split_sweep)
    for name in fused_sweep:
        f, s = fused_sweep[name], split_sweep[name]
        np.testing.assert_array_equal(f.rolled, s.rolled)
        for leaf_f, leaf_s in zip(jax.tree.leaves(f.cs), jax.tree.leaves(s.cs)):
            np.testing.assert_array_equal(leaf_f, leaf_s)
        for leaf_f, leaf_s in zip(
            jax.tree.leaves(f.deciles), jax.tree.leaves(s.deciles)
        ):
            np.testing.assert_array_equal(leaf_f, leaf_s)
        assert f.decile_params == s.decile_params
