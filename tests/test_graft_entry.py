"""Driver-facing entry points must work with NO environment preparation.

Round-1 VERDICT item 1: ``dryrun_multichip`` failed on the 1-chip host
because nothing provisioned the virtual device mesh. These tests run the
entry points in clean subprocesses (the driver's invocation style) so a
regression shows up here before it shows up in MULTICHIP_r{N}.json.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = {k: v for k, v in os.environ.items()}
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.pop("JAX_ENABLE_X64", None)
    # Hosts that tunnel to a remote accelerator may inject a sitecustomize
    # (via PYTHONPATH) whose PJRT hook dials the tunnel at backend init even
    # when JAX_PLATFORMS=cpu; a dead tunnel then hangs the subprocess
    # forever. These tests validate OUR entry points, not the host's relay —
    # drop such injected site dirs from the child's path.
    if "PYTHONPATH" in env:
        parts = [
            p for p in env["PYTHONPATH"].split(os.pathsep)
            if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))
        ]
        if parts:
            env["PYTHONPATH"] = os.pathsep.join(parts)
        else:
            env.pop("PYTHONPATH")
    return env


def test_dryrun_multichip_self_provisions():
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)",
        ],
        cwd=REPO,
        env=_clean_env(),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"


def test_entry_compiles_and_runs():
    # Pinned to CPU: the contract under test is "entry() returns a jittable
    # program", not "this host's accelerator tunnel is healthy" — a hung
    # remote TPU client must not fail the suite (the driver compile-checks
    # entry() on real hardware separately).
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import jax\n"
            "from __graft_entry__ import entry\n"
            "fn, args = entry()\n"
            "out = jax.jit(fn)(*args)\n"
            "jax.block_until_ready(out)\n",
        ],
        cwd=REPO,
        env={**_clean_env(), "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
