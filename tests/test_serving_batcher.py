"""Microbatcher + bucketed-executor property tests.

Hand-rolled property sweeps (no hypothesis in the image): bucket choice is
monotone and power-of-two, padding is an exact no-op on real rows, and a
full queue raises the documented backpressure error instead of blocking —
guarded by thread-join timeouts so a regression fails instead of hanging
the suite. Stress variants are marked ``slow``.
"""

import threading
import time

import numpy as np
import pytest

from fm_returnprediction_tpu.serving import (
    MicroBatcher,
    QueueFullError,
    build_serving_state,
)
from fm_returnprediction_tpu.serving.executor import (
    BucketedExecutor,
    bucket_for,
    bucket_sizes,
)


def _tiny_state(rng, t=50, n=30, p=2):
    x = rng.standard_normal((t, n, p))
    y = x @ np.array([0.5, -0.25]) + 0.01 * rng.standard_normal((t, n))
    mask = rng.random((t, n)) > 0.1
    y = np.where(mask, y, np.nan)
    x = np.where(mask[..., None], x, np.nan)
    return build_serving_state(y, x, mask, window=20, min_periods=10)


# -- bucketing properties --------------------------------------------------


def test_bucket_ladder_is_powers_of_two():
    for max_batch in (1, 2, 3, 7, 8, 64, 100, 256):
        ladder = bucket_sizes(max_batch)
        assert all(b & (b - 1) == 0 for b in ladder)
        assert ladder[-1] >= max_batch
        assert list(ladder) == sorted(set(ladder))


def test_bucket_choice_is_monotone_and_minimal():
    """Property: over every request size up to max_batch, the bucket is the
    SMALLEST ladder rung that fits, and n → bucket_for(n) is monotone
    non-decreasing."""
    for max_batch in (8, 64, 100):
        prev = 0
        for n in range(1, max_batch + 1):
            b = bucket_for(n, max_batch)
            assert b >= n
            assert b >= prev  # monotone
            smaller = [r for r in bucket_sizes(max_batch) if r < b]
            assert all(r < n for r in smaller)  # minimal
            prev = b


def test_bucket_for_rejects_nonsense():
    with pytest.raises(ValueError):
        bucket_for(0, 8)
    with pytest.raises(ValueError):
        bucket_for(9, 8)  # past max_batch: caller must split
    with pytest.raises(ValueError):
        # the cap is max_batch ITSELF, not the rounded-up ladder top —
        # 101 rows would physically fit the 128 bucket but the knob says 100
        bucket_for(101, 100)
    with pytest.raises(ValueError):
        bucket_sizes(0)


def test_min_bucket_floors_the_ladder():
    assert bucket_sizes(64, min_bucket=8)[0] == 8
    assert bucket_for(1, 64, min_bucket=8) == 8


# -- padding is an exact no-op --------------------------------------------


def test_padding_never_changes_results(rng):
    """Property: for every batch size 1..max_batch, running the same rows
    through a padded bucket equals running them alone — bit-identical (the
    masking discipline: a padding row is an exact no-op)."""
    state = _tiny_state(rng)
    exe = BucketedExecutor(state, max_batch=16)
    exe.warmup()
    t = state.n_months
    full = exe.run(
        np.arange(16) % t,
        np.asarray([np.zeros(2) + 0.1 * k for k in range(16)]),
    )
    for size in range(1, 17):
        got = exe.run(
            np.arange(size) % t,
            np.asarray([np.zeros(2) + 0.1 * k for k in range(size)]),
        )
        # same row, same bucket-or-not: results must agree exactly
        np.testing.assert_array_equal(got, full[:size])


def test_padding_rows_never_leak(rng):
    """A batch of one in the 16-bucket returns exactly one value, and a NaN
    feature row yields NaN (not a padded zero-row's projection)."""
    state = _tiny_state(rng)
    exe = BucketedExecutor(state, max_batch=16)
    out = exe.run(np.asarray([40]), np.asarray([[np.nan, 0.0]]))
    assert out.shape == (1,)
    assert np.isnan(out[0])


# -- backpressure ----------------------------------------------------------


def test_full_queue_raises_queue_full_error():
    """The documented backpressure contract: submit on a full queue raises
    QueueFullError immediately (no auto-flusher draining it)."""
    batcher = MicroBatcher(
        lambda m, x, v: np.zeros(len(m)),
        max_batch=4, max_queue=3, auto_flush=False,
    )
    for k in range(3):
        batcher.submit(0, np.zeros(2))
    with pytest.raises(QueueFullError):
        batcher.submit(0, np.zeros(2))
    # draining frees capacity again
    assert batcher.drain() == 3
    batcher.submit(0, np.zeros(2))
    assert batcher.stats()["n_rejected"] == 1


def test_full_queue_raise_does_not_block():
    """Guard: the rejecting submit must return within the timeout even while
    the runner is stalled mid-batch (the failure mode this contract exists
    to prevent is blocking forever)."""
    release = threading.Event()

    def stalled_runner(m, x, v):
        release.wait(10.0)
        return np.zeros(len(m))

    batcher = MicroBatcher(
        stalled_runner, max_batch=2, max_latency_ms=0.1, max_queue=2,
        auto_flush=True,
    )
    try:
        # saturate: 2 in-flight via the flusher + keep the queue full
        outcome = {}

        def producer():
            errors = 0
            for _ in range(50):
                try:
                    batcher.submit(0, np.zeros(2))
                except QueueFullError:
                    errors += 1
            outcome["rejected"] = errors

        th = threading.Thread(target=producer)
        th.start()
        th.join(timeout=5.0)
        assert not th.is_alive(), "submit blocked instead of raising"
        assert outcome["rejected"] > 0
    finally:
        release.set()
        batcher.close()


def test_closed_batcher_rejects():
    batcher = MicroBatcher(
        lambda m, x, v: np.zeros(len(m)), auto_flush=False
    )
    batcher.close()
    with pytest.raises(RuntimeError):
        batcher.submit(0, np.zeros(2))


def test_close_without_flusher_drains_pending():
    """close() may never leave a future dangling: with no flusher thread it
    drains synchronously instead of letting callers time out."""
    batcher = MicroBatcher(
        lambda m, x, v: np.zeros(len(m)), auto_flush=False
    )
    fut = batcher.submit(0, np.zeros(2))
    batcher.close()
    assert fut.result(timeout=1.0) == 0.0


def test_malformed_row_fails_alone_not_its_batch():
    """A wrong-shape feature row is rejected at submit (ValueError for that
    request only); a batch-mate submitted in the same window still runs."""
    batcher = MicroBatcher(
        lambda m, x, v: np.zeros(len(m)), auto_flush=False, n_predictors=2
    )
    good = batcher.submit(0, np.zeros(2))
    with pytest.raises(ValueError):
        batcher.submit(0, np.zeros(7))
    with pytest.raises(ValueError):
        batcher.submit(0, np.zeros((2, 2)))
    batcher.flush()
    assert good.result(timeout=1.0) == 0.0
    batcher.close()


def test_flusher_survives_errors_and_batches_are_width_homogeneous():
    """The flusher thread must outlive both a failing runner and malformed
    submissions: a runner exception lands on its batch's futures and later
    requests still get served; with no declared n_predictors a wrong-width
    row sinks in a batch OF ITS OWN KIND (never poisoning differently
    shaped batch-mates in np.stack, never pinning the batcher to a bad
    first request's width)."""
    calls = {"n": 0}

    def picky(m, x, v):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient backend fault")
        if x.shape[1] != 2:
            raise ValueError(f"state expects 2 predictors, got {x.shape[1]}")
        return np.full(len(m), 7.0)

    batcher = MicroBatcher(picky, max_batch=4, max_latency_ms=0.5,
                           auto_flush=True)
    try:
        doomed = batcher.submit(0, np.zeros(2))
        with pytest.raises(RuntimeError, match="transient"):
            doomed.result(timeout=5.0)
        # a malformed FIRST-of-its-window row must not brick the batcher:
        # it fails alone (its own batch), the well-formed row still serves
        bad = batcher.submit(0, np.zeros(3))
        ok = batcher.submit(0, np.zeros(2))
        with pytest.raises(ValueError, match="2 predictors"):
            bad.result(timeout=5.0)
        assert ok.result(timeout=5.0) == 7.0
    finally:
        batcher.close()


def test_min_bucket_above_max_batch_fails_fast(rng):
    with pytest.raises(ValueError):
        bucket_sizes(4, min_bucket=8)
    with pytest.raises(ValueError):
        BucketedExecutor(_tiny_state(rng), max_batch=4, min_bucket=8)


def test_close_with_stalled_runner_fails_queued_futures():
    """close() may never silently strand a future: when the flusher cannot
    drain within the timeout (runner stalled mid-batch), the still-queued
    requests fail with RuntimeError instead of hanging their callers."""
    release = threading.Event()

    def stalled_runner(m, x, v):
        release.wait(10.0)
        return np.zeros(len(m))

    batcher = MicroBatcher(
        stalled_runner, max_batch=1, max_latency_ms=0.1, max_queue=8,
        auto_flush=True,
    )
    try:
        in_flight = batcher.submit(0, np.zeros(2))  # taken by the flusher
        time.sleep(0.05)
        queued = [batcher.submit(0, np.zeros(2)) for _ in range(3)]
        batcher.close(timeout=0.2)
        for fut in queued:
            with pytest.raises(RuntimeError, match="stalled"):
                fut.result(timeout=1.0)
    finally:
        release.set()
    # the batch already inside the runner still completes normally
    assert in_flight.result(timeout=5.0) == 0.0


def test_occupancy_is_rows_per_dispatched_slot():
    """Occupancy counts rows per DISPATCHED bucket slot, so it mirrors the
    executor's ladder: 2 rows in a min_bucket=8 dispatch is 0.25, not a
    flattering 2/2 = 1.0 — the metric exists to expose exactly that
    padding waste."""
    batcher = MicroBatcher(
        lambda m, x, v: np.zeros(len(m)),
        max_batch=16, min_bucket=8, auto_flush=False,
    )
    for _ in range(2):
        batcher.submit(0, np.zeros(2))
    batcher.flush()
    assert batcher.stats()["batch_occupancy"] == pytest.approx(2 / 8)
    batcher.close()

    batcher = MicroBatcher(
        lambda m, x, v: np.zeros(len(m)), max_batch=16, auto_flush=False
    )
    for _ in range(3):
        batcher.submit(0, np.zeros(2))
    batcher.flush()
    assert batcher.stats()["batch_occupancy"] == pytest.approx(3 / 4)
    batcher.close()


def test_runner_exception_delivered_to_futures():
    def boom(m, x, v):
        raise RuntimeError("backend fault")

    batcher = MicroBatcher(boom, auto_flush=False)
    fut = batcher.submit(0, np.zeros(2))
    batcher.flush()
    with pytest.raises(RuntimeError, match="backend fault"):
        fut.result(timeout=1.0)
    batcher.close()


def test_latency_deadline_flushes_a_lone_request(rng):
    """A single query never waits for a batch that isn't coming: the
    max-latency knob flushes it."""
    state = _tiny_state(rng)
    exe = BucketedExecutor(state, max_batch=64)
    exe.warmup()
    batcher = MicroBatcher(
        exe.run, max_batch=64, max_latency_ms=5.0, auto_flush=True
    )
    try:
        fut = batcher.submit(25, np.zeros(2))
        assert isinstance(fut.result(timeout=5.0), float)
    finally:
        batcher.close()


@pytest.mark.slow
def test_stress_many_producers_tiny_queue(rng):
    """Stress: 8 producers hammer a queue of 16 with a slow runner; every
    submit either resolves or raises QueueFullError — nothing deadlocks,
    nothing is lost, accounting adds up."""
    state = _tiny_state(rng)
    exe = BucketedExecutor(state, max_batch=8)
    exe.warmup()

    def slow_runner(m, x, v):
        time.sleep(0.002)
        return exe.run(m, x, v)

    batcher = MicroBatcher(
        slow_runner, max_batch=8, max_latency_ms=0.5, max_queue=16,
        auto_flush=True,
    )
    done = np.zeros(8, dtype=np.int64)
    rejected = np.zeros(8, dtype=np.int64)

    def producer(k):
        futures = []
        for _ in range(200):
            try:
                futures.append(batcher.submit(25, np.zeros(2)))
            except QueueFullError:
                rejected[k] += 1
        for fut in futures:
            fut.result(timeout=30.0)
        done[k] = len(futures)

    threads = [threading.Thread(target=producer, args=(k,)) for k in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60.0)
    assert not any(th.is_alive() for th in threads), "stress run deadlocked"
    stats = batcher.stats()
    batcher.close()
    assert done.sum() + rejected.sum() == 8 * 200
    assert stats["n_done"] == done.sum()
    assert stats["n_rejected"] == rejected.sum()
    assert exe.misses == 0  # still no query-time compiles under stress
