"""``parallel.mesh.place_global`` — the one placement primitive for every
sharded path. Single-process behaviors here (the fully-addressable fast
path and input-kind handling); the cross-process branches are exercised for
real by ``tests/test_multiprocess.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from fm_returnprediction_tpu.parallel import make_mesh, place_global


def _mesh():
    return make_mesh(axis_name="firms")


def test_numpy_nan_payload_round_trips():
    mesh = _mesh()
    x = np.arange(32.0, dtype=np.float32).reshape(4, 8)
    x[0, 0] = np.nan  # the padding case that broke cross-process device_put
    placed = place_global(x, NamedSharding(mesh, P(None, "firms")))
    assert placed.sharding.spec == P(None, "firms")
    np.testing.assert_array_equal(np.asarray(placed), x)


def test_jax_array_and_replicated_spec():
    mesh = _mesh()
    x = jnp.linspace(0, 1, 16)
    placed = place_global(x, NamedSharding(mesh, P()))
    assert placed.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(placed), np.asarray(x))


def test_typed_prng_keys_stay_usable():
    mesh = _mesh()
    keys = jax.random.split(jax.random.key(7), mesh.devices.size * 2)
    placed = place_global(keys, NamedSharding(mesh, P("firms")))
    assert jnp.issubdtype(placed.dtype, jax.dtypes.prng_key)
    # identical stream: placement must not alter key material
    want = jax.random.uniform(keys[3], (2,))
    got = jax.random.uniform(placed[3], (2,))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bool_mask_payload():
    mesh = _mesh()
    m = np.arange(24).reshape(3, 8) % 3 == 0
    placed = place_global(m, NamedSharding(mesh, P(None, "firms")))
    assert placed.dtype == jnp.bool_
    np.testing.assert_array_equal(np.asarray(placed), m)


def test_pipeline_mesh_policy(monkeypatch):
    """Single-process: MESH_DEVICES opt-in (None at the default of 1).
    The multi-process branch (months×firms hierarchy regardless of
    MESH_DEVICES) is exercised by tests/test_multiprocess.py."""
    from fm_returnprediction_tpu import settings
    from fm_returnprediction_tpu.parallel import pipeline_mesh

    monkeypatch.setitem(settings.d, "MESH_DEVICES", 1)
    assert pipeline_mesh() is None
    monkeypatch.setitem(settings.d, "MESH_DEVICES", 8)
    mesh = pipeline_mesh()
    assert mesh is not None and mesh.devices.size == 8
