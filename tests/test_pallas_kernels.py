"""Fused pallas rolling kernels vs the XLA path and the pandas oracle.

Runs in interpreter mode on the CPU test backend; the TPU compile path is
exercised by bench.py / the driver's compile check on real hardware.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.ops.pallas_kernels import (
    masked_cumulative_moments,
    rolling_mean_fused,
    rolling_std_fused,
    rolling_sum_fused,
)
from fm_returnprediction_tpu.ops.rolling import (
    resolve_rolling_route,
    rolling_mean,
    rolling_std,
    rolling_sum,
)

pytestmark = pytest.mark.kernels


@pytest.fixture(scope="module")
def noisy_panel():
    rng = np.random.default_rng(23)
    x = 0.02 * rng.standard_normal((700, 40))
    x[rng.random(x.shape) < 0.07] = np.nan
    return x


def test_moments_match_numpy(noisy_panel):
    x = noisy_panel
    csum, csumsq, ccnt = masked_cumulative_moments(
        jnp.asarray(x), block_t=128, block_n=128, interpret=True
    )
    finite = np.isfinite(x)
    xz = np.where(finite, x, 0.0)
    np.testing.assert_allclose(np.asarray(csum), np.cumsum(xz, 0),
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(np.asarray(csumsq), np.cumsum(xz * xz, 0),
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(np.asarray(ccnt), np.cumsum(finite, 0))


def test_moments_padding_and_carry(noisy_panel):
    """T and N not multiples of the block sizes → padding is dropped and the
    carry crosses T-block boundaries correctly."""
    x = noisy_panel[:391, :37]
    csum, _, ccnt = masked_cumulative_moments(
        jnp.asarray(x), block_t=64, block_n=128, interpret=True
    )
    assert csum.shape == x.shape
    xz = np.where(np.isfinite(x), x, 0.0)
    np.testing.assert_allclose(np.asarray(csum), np.cumsum(xz, 0),
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(np.asarray(ccnt)[-1], np.isfinite(x).sum(0))


def test_rolling_std_fused_matches_xla_and_pandas(noisy_panel):
    x = noisy_panel
    window, min_periods = 252, 100
    fused = np.asarray(rolling_std_fused(
        jnp.asarray(x), window, min_periods,
        block_t=128, block_n=128, interpret=True,
    ))
    xla = np.asarray(rolling_std(jnp.asarray(x), window, min_periods))
    np.testing.assert_allclose(fused, xla, rtol=1e-7, atol=1e-10, equal_nan=True)

    want = (
        pd.DataFrame(x).rolling(window, min_periods=min_periods).std().to_numpy()
    )
    np.testing.assert_allclose(fused, want, rtol=1e-6, atol=1e-9, equal_nan=True)


def test_rolling_std_fused_short_series():
    x = np.full((10, 3), np.nan)
    x[2:, 1] = 1.0
    out = np.asarray(rolling_std_fused(
        jnp.asarray(x), window=5, min_periods=2,
        block_t=8, block_n=128, interpret=True,
    ))
    want = pd.DataFrame(x).rolling(5, min_periods=2).std().to_numpy()
    np.testing.assert_allclose(out, want, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_rolling_std_dispatch_override(noisy_panel, monkeypatch):
    """FMRP_PALLAS=0 forces the XLA path even off-CPU; explicit
    use_pallas=False always wins; both paths agree."""
    import jax.numpy as jnp

    x = jnp.asarray(noisy_panel[:100, :10])
    monkeypatch.setenv("FMRP_PALLAS", "0")
    from fm_returnprediction_tpu.ops.rolling import _pallas_default

    assert not _pallas_default()
    monkeypatch.setenv("FMRP_PALLAS", "1")
    assert _pallas_default()
    a = rolling_std(x, 20, 5, use_pallas=False)
    monkeypatch.delenv("FMRP_PALLAS")
    b = rolling_std(x, 20, 5)  # CPU default → XLA path
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), equal_nan=True)


def test_pallas_flag_disable_spellings(monkeypatch):
    from fm_returnprediction_tpu.ops.rolling import _pallas_default

    monkeypatch.delenv("FMRP_ROLLING_ROUTE", raising=False)
    for off in ("0", "off", "no", "FALSE", ""):
        monkeypatch.setenv("FMRP_PALLAS", off)
        assert not _pallas_default(), off
    for on in ("1", "true", "YES", "on"):
        monkeypatch.setenv("FMRP_PALLAS", on)
        assert _pallas_default(), on


# -- the fused sum/mean siblings (PR 11) ------------------------------------


def test_rolling_sum_mean_fused_match_xla_and_pandas(noisy_panel):
    """The fused kernels vs the XLA cumsum route (same algorithm, so tight)
    and the pandas oracle across min_periods regimes, incl. mask edges."""
    x = noisy_panel
    xj = jnp.asarray(x)
    for window, mp in ((24, 1), (24, 12), (12, 12)):
        fused = np.asarray(rolling_sum_fused(
            xj, window, mp, block_t=128, block_n=128, interpret=True))
        xla = np.asarray(rolling_sum(xj, window, mp, use_pallas=False))
        np.testing.assert_allclose(fused, xla, rtol=1e-5, atol=5e-7,
                                   equal_nan=True)
        want = pd.DataFrame(x).rolling(window, min_periods=mp).sum().to_numpy()
        np.testing.assert_allclose(fused, want, rtol=1e-4, atol=5e-7,
                                   equal_nan=True)

        fusedm = np.asarray(rolling_mean_fused(
            xj, window, mp, block_t=128, block_n=128, interpret=True))
        xlam = np.asarray(rolling_mean(xj, window, mp, use_pallas=False))
        np.testing.assert_allclose(fusedm, xlam, rtol=1e-5, atol=5e-7,
                                   equal_nan=True)
        wantm = pd.DataFrame(x).rolling(window, min_periods=mp).mean().to_numpy()
        np.testing.assert_allclose(fusedm, wantm, rtol=1e-4, atol=5e-7,
                                   equal_nan=True)


def test_rolling_sum_fused_all_nan_column():
    x = np.full((40, 3), np.nan)
    x[:, 0] = 1.0
    out = np.asarray(rolling_sum_fused(
        jnp.asarray(x), 5, 2, block_t=8, block_n=128, interpret=True))
    want = pd.DataFrame(x).rolling(5, min_periods=2).sum().to_numpy()
    np.testing.assert_allclose(out, want, rtol=1e-6, equal_nan=True)
    assert np.isnan(out[:, 1]).all() and np.isnan(out[:, 2]).all()


def test_rolling_route_resolution(monkeypatch):
    import jax

    monkeypatch.delenv("FMRP_PALLAS", raising=False)
    monkeypatch.delenv("FMRP_ROLLING_ROUTE", raising=False)
    platform = jax.devices()[0].platform
    assert resolve_rolling_route() == (
        "pallas" if platform == "tpu" else "xla"
    )
    monkeypatch.setenv("FMRP_ROLLING_ROUTE", "pallas")
    assert resolve_rolling_route() == "pallas"
    monkeypatch.setenv("FMRP_ROLLING_ROUTE", "xla")
    assert resolve_rolling_route() == "xla"
    # the route knob OUTRANKS the legacy boolean; the boolean still works
    # when the knob is unset/auto
    monkeypatch.setenv("FMRP_PALLAS", "1")
    assert resolve_rolling_route() == "xla"
    monkeypatch.setenv("FMRP_ROLLING_ROUTE", "auto")
    assert resolve_rolling_route() == "pallas"
    monkeypatch.setenv("FMRP_ROLLING_ROUTE", "vectorized")
    with pytest.raises(ValueError):
        resolve_rolling_route()
    assert resolve_rolling_route(route="xla") == "xla"  # arg beats env


def test_rolling_sum_mean_route_dispatch_agrees(noisy_panel, monkeypatch):
    """FMRP_ROLLING_ROUTE=xla forces the oracle; the explicit override and
    the default CPU resolution land on the same numbers."""
    x = jnp.asarray(noisy_panel[:100, :10])
    monkeypatch.setenv("FMRP_ROLLING_ROUTE", "xla")
    a = rolling_sum(x, 12, 3)
    monkeypatch.delenv("FMRP_ROLLING_ROUTE")
    b = rolling_sum(x, 12, 3, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    am = rolling_mean(x, 12, 3)   # CPU default → XLA path
    bm = rolling_mean(x, 12, 3, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(am), np.asarray(bm))
